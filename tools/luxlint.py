#!/usr/bin/env python
"""luxlint: project-native static analysis over lux_tpu/ + tools/.

Usage:
    python tools/luxlint.py                  # lint the default tree (AST tier)
    python tools/luxlint.py path.py dir/     # lint specific targets
    python tools/luxlint.py --changed        # only files changed vs git HEAD
    python tools/luxlint.py --json           # full findings as JSON
    python tools/luxlint.py --list-rules     # rule table
    python tools/luxlint.py --select LUX001  # subset of rules
    python tools/luxlint.py --ir             # IR tier: trace every registered
                                             #   program x executor, run LUX1xx
    python tools/luxlint.py --ir fixture.py  # trace a module's TRACES list
    python tools/luxlint.py --plans DIR...   # verify saved GroupedTailPlan
                                             #   artifacts (LUX2xx, jax-free)
    python tools/luxlint.py --threads        # concurrency tier: lock
                                             #   discipline + lock-order graph
                                             #   (LUX3xx, stdlib AST)
    python tools/luxlint.py --exchange       # exchange tier: verify every
                                             #   sharded target's ExchangePlan
                                             #   + collective dataflow (LUX4xx)
    python tools/luxlint.py --exchange DIR   # verify saved exchange-plan
                                             #   artifacts / fixture modules
    python tools/luxlint.py --tune DIR...    # verify saved tuneconf.v1
                                             #   auto-tuner artifacts
                                             #   (LUX5xx, jax-free)
    python tools/luxlint.py --programs       # program-contract tier: prove
                                             #   each registry program's GAS
                                             #   algebra + derive the
                                             #   capability matrix (LUX6xx)
    python tools/luxlint.py --programs f.py  # prove programs defined in
                                             #   fixture modules instead
    python tools/luxlint.py --memory         # memory tier: donation-aware
                                             #   HBM-footprint walk over every
                                             #   traced registry target +
                                             #   memcap.v1 contracts (LUX7xx)
    python tools/luxlint.py --memory f.py    # check fixture modules' TARGETS/
                                             #   MODELS/MEMCAP instead
    python tools/luxlint.py --baseline F     # snapshot/compare: only findings
                                             #   absent from F fail the run

Exit status: 0 clean, 1 unsuppressed findings or syntax/trace errors.
Always emits one greppable summary line (`LUXLINT {...}`, the merge_smoke
idiom) so CI logs carry the verdict even when output scrolls.

Suppress an AST-tier finding inline, with a reason:
    x.item()  # luxlint: disable=LUX001 -- intended once-per-run sync
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from lux_tpu.analysis import all_rules, run_paths  # noqa: E402
from lux_tpu.analysis.threads import all_thread_rules, run_threads  # noqa: E402

DEFAULT_TARGETS = ("lux_tpu", "tools", "bench.py")


def _changed_paths() -> list:
    """Python files changed vs HEAD (staged + unstaged + untracked)."""
    out = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            text = subprocess.run(cmd, cwd=_REPO, capture_output=True,
                                  text=True, check=True).stdout
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"luxlint: --changed: {' '.join(cmd)} failed: {e}",
                  file=sys.stderr)
            return []
        for line in text.splitlines():
            if line.endswith(".py"):
                p = os.path.join(_REPO, line)
                if os.path.isfile(p):
                    out.add(p)
    return sorted(out)


_SPAN_CACHE: dict = {}


def _span_hash(path: str, line: int) -> str:
    """Content hash of the finding's source span: sha1 (16 hex chars) of
    the stripped text of the flagged line. Returns "" when the path is
    virtual (trace targets, plan dirs) or the line is out of range."""
    if line < 1:
        return ""
    if path not in _SPAN_CACHE:
        try:
            with open(path, "rb") as fh:
                _SPAN_CACHE[path] = fh.read().splitlines()
        except OSError:
            _SPAN_CACHE[path] = None
    lines = _SPAN_CACHE[path]
    if lines is None or line > len(lines):
        return ""
    return hashlib.sha1(lines[line - 1].strip()).hexdigest()[:16]


def _baseline_key(path: str, f) -> str:
    """Ratchet key: (rule, path, source-span hash). Hashing the flagged
    line's content instead of its number keeps keys stable across
    unrelated edits that shift line numbers; renaming/rewriting the
    flagged line itself re-opens the finding, which is the point of a
    ratchet. Virtual paths (IR targets, plan artifacts) have no source
    to hash and fall back to the message."""
    span = _span_hash(path, f.line)
    return f"{f.rule}\t{path}\t{span or f.message}"


def _apply_baseline(report, baseline_path: str) -> int:
    """Snapshot-or-compare. Missing file: write current findings, pass.
    Present: fail only on findings whose _baseline_key is new. Line
    numbers are deliberately not part of the key — unrelated edits
    shift them."""
    current = {}
    for res in report.results:
        for f in res.findings:
            current.setdefault(_baseline_key(res.path, f), []).append((res, f))
    if not os.path.exists(baseline_path):
        with open(baseline_path, "w") as fh:
            json.dump({"schema": report.schema + ".baseline",
                       "keys": sorted(current)}, fh, indent=0)
        print(f"luxlint: baseline written: {baseline_path} "
              f"({len(current)} finding keys)")
        return 0
    with open(baseline_path) as fh:
        known = set(json.load(fh).get("keys", ()))
    new = sorted(k for k in current if k not in known)
    errors = [r for r in report.results if r.error]
    for k in new:
        res, f = current[k][0]
        print(f"{res.path}:{f.line}:{f.col}: {f.rule} {f.message}  [new]")
    print(f"luxlint: baseline {baseline_path}: {len(new)} new / "
          f"{len(current)} total findings, {len(errors)} errors")
    return 1 if new or errors else 0


def _run_ir(paths, select: str):
    """IR tier: trace registered programs (or fixture modules) and run the
    LUX1xx jaxpr rules. Mirrors tests/conftest.py's env: 8 virtual CPU
    devices, CPU platform — set BEFORE jax initializes a backend, so the
    sharded executors have devices to shard over."""
    from lux_tpu.utils.platform import virtual_cpu_flags
    os.environ.setdefault("XLA_FLAGS", virtual_cpu_flags(8))
    os.environ["JAX_PLATFORMS"] = "cpu"
    from lux_tpu.analysis import ir

    if paths:
        targets = []
        for p in paths:
            targets.extend(ir.load_fixture_targets(p))
    else:
        targets = ir.registry_targets()
    rules = ir.all_ir_rules()
    if select:
        want = {s.strip() for s in select.split(",") if s.strip()}
        rules = [r for r in rules if r.id in want]
    return ir.run_targets(targets, rules)


def _run_exchange(paths, select: str):
    """Exchange tier: verify ExchangePlan tables (LUX401-403) and the
    collective-dataflow contract (LUX404-406). With no paths, the whole
    compact+full sharded registry matrix; with paths, saved artifact
    dirs and/or fixture modules exporting TRACES / PLANS. The analysis
    only ever traces and stages tiny placement programs, so XLA's
    backend optimizer is dead weight — turning it off roughly halves
    the tier's wall cost."""
    from lux_tpu.utils.platform import virtual_cpu_flags
    os.environ.setdefault(
        "XLA_FLAGS",
        virtual_cpu_flags(8) + " --xla_backend_optimization_level=0")
    os.environ["JAX_PLATFORMS"] = "cpu"
    from lux_tpu.analysis import ir

    want = None
    if select:
        want = {s.strip() for s in select.split(",") if s.strip()}
    if paths:
        return ir.run_exchange_paths(paths, select=want)
    return ir.run_exchange_matrix(select=want)


def _run_plans(paths, select: str):
    from lux_tpu.analysis import planck
    rules = planck.all_plan_rules()
    if select:
        want = {s.strip() for s in select.split(",") if s.strip()}
        rules = [r for r in rules if r.id in want]
    return planck.verify_plan_dirs(paths, rules)


def _run_tune(paths, select: str):
    from lux_tpu.analysis import tuneck
    rules = tuneck.all_tune_rules()
    if select:
        want = {s.strip() for s in select.split(",") if s.strip()}
        rules = [r for r in rules if r.id in want]
    return tuneck.verify_artifact_paths(paths, rules)


def _run_programs(paths, select: str, gascap_out: str):
    """Program-contract tier: prove combiner identity/algebra, direction
    duality, frontier annihilation, and monotone convergence (LUX601-606)
    per program. Host numpy drives the probes; program hooks run as
    eager cpu jnp, so no virtual device mesh is needed. With no paths,
    the registered programs — and a clean run regenerates the gascap.v1
    capability artifact when --gascap-out names a destination. With
    paths, fixture modules defining programs (gascap-out is registry-
    only: fixtures prove rules, they don't define serving capability)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    from lux_tpu.analysis import gasck

    want = None
    if select:
        want = tuple(s.strip() for s in select.split(",") if s.strip())
    if paths:
        return gasck.verify_fixture_paths(paths, select=want)
    return gasck.verify_registry(select=want,
                                 capmap_out=gascap_out or None)


def _run_memory(paths, select: str, memcap_out: str):
    """Memory tier: walk buffer liveness over every traced registry
    target (LUX701-706) and keep the memcap.v1 footprint artifact
    honest. Needs the same 8-virtual-device CPU mesh as --ir so the
    sharded executors have devices to shard over. With paths, fixture
    modules supply TARGETS/MODELS/MEMCAP/COMMITTED instead (memcap-out
    is registry-only: fixtures prove rules, they don't price serving)."""
    from lux_tpu.utils.platform import virtual_cpu_flags
    os.environ.setdefault(
        "XLA_FLAGS",
        virtual_cpu_flags(8) + " --xla_backend_optimization_level=0")
    os.environ["JAX_PLATFORMS"] = "cpu"
    from lux_tpu.analysis import memck

    want = None
    if select:
        want = tuple(s.strip() for s in select.split(",") if s.strip())
    if paths:
        return memck.verify_fixture_paths(paths, select=want)
    return memck.verify_registry(select=want,
                                 memcap_out=memcap_out or None)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="luxlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS}); "
                         "with --ir: fixture modules exporting TRACES; "
                         "with --plans: saved plan artifact dirs")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report as JSON on stdout")
    ap.add_argument("--select", default="",
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    ap.add_argument("--ir", action="store_true",
                    help="run the jaxpr tier (LUX101-105) over every "
                         "registered program x executor, or over the TRACES "
                         "of the given fixture modules")
    ap.add_argument("--plans", action="store_true",
                    help="verify saved GroupedTailPlan artifact dirs "
                         "(LUX201-205; jax-free, mmap load)")
    ap.add_argument("--threads", action="store_true",
                    help="run the concurrency tier (LUX301-305): thread-"
                         "shared state, lock-order graph, blocking-under-"
                         "lock, unjoined threads, publish discipline")
    ap.add_argument("--exchange", action="store_true",
                    help="run the exchange tier (LUX401-406): ExchangePlan "
                         "structure/coverage/profitability plus the "
                         "overlap-proof, sentinel-annihilator, and byte-"
                         "accounting dataflow rules over every sharded "
                         "registry target; with paths, verify saved "
                         "exchange artifacts or fixture modules")
    ap.add_argument("--tune", action="store_true",
                    help="verify saved tuneconf.v1 auto-tuner artifacts "
                         "(LUX501-504: structure, knob domains, selection "
                         "consistency, staleness; jax-free)")
    ap.add_argument("--programs", action="store_true",
                    help="run the program-contract tier (LUX601-606): "
                         "prove combiner identity/exactness, push/pull "
                         "duality, frontier annihilation, and monotone "
                         "convergence for every registered program and "
                         "derive the gascap.v1 capability matrix; with "
                         "paths, prove fixture-module programs instead")
    ap.add_argument("--gascap-out", default="", metavar="FILE",
                    help="with --programs (registry mode): write the "
                         "derived gascap.v1 capability artifact here when "
                         "the run is clean")
    ap.add_argument("--memory", action="store_true",
                    help="run the memory tier (LUX701-706): donation-aware "
                         "buffer-liveness walk over every traced registry "
                         "target deriving per-device peak live bytes and "
                         "the closed-form footprint model serving admission "
                         "trusts; with paths, check fixture modules' "
                         "TARGETS/MODELS/MEMCAP instead")
    ap.add_argument("--memcap-out", default="", metavar="FILE",
                    help="with --memory (registry mode): write the derived "
                         "memcap.v1 footprint artifact here when the run "
                         "is clean (committed-artifact rules are skipped "
                         "on a regeneration run)")
    ap.add_argument("--changed", action="store_true",
                    help="AST/threads tiers: restrict to .py files changed "
                         "vs git HEAD (plus untracked); the threads tier "
                         "still builds its lock-order graph over the whole "
                         "tree")
    ap.add_argument("--baseline", default="",
                    help="snapshot file: if missing, write current findings "
                         "and pass; if present, fail only on new findings")
    args = ap.parse_args(argv)

    if sum((args.ir, args.plans, args.threads, args.exchange,
            args.tune, args.programs, args.memory)) > 1:
        ap.error("--ir, --plans, --threads, --exchange, --tune, "
                 "--programs, and --memory are separate tiers; run them "
                 "separately")

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}  {r.title}\n       {r.doc}")
        for r in all_thread_rules():
            print(f"{r.id}  {r.title}\n       {r.doc}")
        # The IR/plan tiers import numpy/jax; keep --list-rules instant by
        # documenting them from their modules only when importable cheaply.
        try:
            from lux_tpu.analysis import planck
            for r in planck.all_plan_rules():
                print(f"{r.id}  {r.title}\n       {r.doc}")
        except Exception:
            pass
        try:
            from lux_tpu.analysis import exchck
            for r in exchck.all_exchange_rules():
                print(f"{r.id}  {r.title}\n       {r.doc}")
        except Exception:
            pass
        try:
            from lux_tpu.analysis import tuneck
            for r in tuneck.all_tune_rules():
                print(f"{r.id}  {r.title}\n       {r.doc}")
        except Exception:
            pass
        try:
            from lux_tpu.analysis import gasck
            for r in gasck.all_program_rules():
                print(f"{r.id}  {r.title}\n       {r.doc}")
        except Exception:
            pass
        try:
            from lux_tpu.analysis import memck
            for r in memck.all_memory_rules():
                print(f"{r.id}  {r.title}\n       {r.doc}")
        except Exception:
            pass
        print("LUX101-105  jaxpr tier (dtype drift, host callbacks, "
              "footprint, donation, collectives) — run with --ir")
        print("LUX404-406  exchange dataflow tier (overlap proof, sentinel "
              "annihilation, byte accounting) — run with --exchange")
        print("LUX701-706  memory tier (HBM-footprint contracts + the "
              "memcap.v1 serving admission formula) — run with --memory")
        return 0

    if args.ir:
        report = _run_ir(args.paths, args.select)
    elif args.exchange:
        if args.changed and not args.paths:
            # The matrix verifies live engine/partition behaviour, not
            # file text: skip it entirely unless an exchange-relevant
            # source file changed.
            relevant = ("lux_tpu/engine/", "lux_tpu/parallel/",
                        "lux_tpu/graph/", "lux_tpu/analysis/",
                        "lux_tpu/models", "lux_tpu/obs/")
            changed = [p for p in _changed_paths()
                       if os.path.relpath(p, _REPO).startswith(relevant)]
            if not changed:
                print("luxlint: --changed: no exchange-relevant files "
                      "modified")
                print("LUXLINT " + json.dumps(
                    {"schema": "luxlint-exchange.v1", "files": 0,
                     "findings": 0, "errors": 0, "ok": True},
                    sort_keys=True))
                return 0
        report = _run_exchange(args.paths, args.select)
    elif args.plans:
        if not args.paths:
            ap.error("--plans requires at least one artifact directory")
        report = _run_plans(args.paths, args.select)
    elif args.tune:
        if not args.paths:
            ap.error("--tune requires at least one artifact file or "
                     "directory")
        report = _run_tune(args.paths, args.select)
    elif args.programs:
        if args.changed and not args.paths:
            # The tier proves live program algebra, not file text: skip
            # it unless a program-relevant source file changed.
            relevant = ("lux_tpu/models", "lux_tpu/engine/",
                        "lux_tpu/analysis/", "lux_tpu/ops/",
                        "lux_tpu/graph/")
            changed = [p for p in _changed_paths()
                       if os.path.relpath(p, _REPO).startswith(relevant)]
            if not changed:
                print("luxlint: --changed: no program-relevant files "
                      "modified")
                print("LUXLINT " + json.dumps(
                    {"schema": "luxlint-programs.v1", "files": 0,
                     "findings": 0, "errors": 0, "ok": True},
                    sort_keys=True))
                return 0
        report = _run_programs(args.paths, args.select, args.gascap_out)
    elif args.memory:
        if args.changed and not args.paths:
            # The tier prices live engine residency, not file text: skip
            # it unless a footprint-relevant source file changed.
            relevant = ("lux_tpu/engine/", "lux_tpu/analysis/",
                        "lux_tpu/serve/", "lux_tpu/obs/",
                        "lux_tpu/models", "lux_tpu/graph/",
                        "lux_tpu/parallel/")
            changed = [p for p in _changed_paths()
                       if os.path.relpath(p, _REPO).startswith(relevant)]
            if not changed:
                print("luxlint: --changed: no memory-relevant files "
                      "modified")
                print("LUXLINT " + json.dumps(
                    {"schema": "luxlint-memory.v1", "files": 0,
                     "findings": 0, "errors": 0, "ok": True},
                    sort_keys=True))
                return 0
        report = _run_memory(args.paths, args.select, args.memcap_out)
    elif args.threads:
        select = None
        if args.select:
            select = {s.strip() for s in args.select.split(",") if s.strip()}
            unknown = select - {r.id for r in all_thread_rules()}
            if unknown:
                ap.error(f"unknown rule id(s): {sorted(unknown)}")
        tree = [os.path.join(_REPO, t) for t in DEFAULT_TARGETS]
        if args.changed:
            paths = _changed_paths()
            if not paths:
                print("luxlint: --changed: no modified .py files")
                print("LUXLINT " + json.dumps(
                    {"schema": "luxlint-threads.v1", "files": 0,
                     "findings": 0, "errors": 0, "ok": True},
                    sort_keys=True))
                return 0
            graph_paths = tree   # order graph stays whole-tree
        elif args.paths:
            paths = args.paths
            graph_paths = paths  # explicit targets are self-contained
        else:
            paths = graph_paths = tree
        report = run_threads(paths, select=select, graph_paths=graph_paths)
    else:
        rules = all_rules()
        if args.select:
            want = {s.strip() for s in args.select.split(",") if s.strip()}
            unknown = want - {r.id for r in rules}
            if unknown:
                ap.error(f"unknown rule id(s): {sorted(unknown)}")
            rules = [r for r in rules if r.id in want]
        if args.changed:
            paths = _changed_paths()
            if not paths:
                print("luxlint: --changed: no modified .py files")
                print("LUXLINT " + json.dumps(
                    {"schema": "luxlint.v1", "files": 0, "findings": 0,
                     "errors": 0, "ok": True}, sort_keys=True))
                return 0
        else:
            paths = args.paths or [os.path.join(_REPO, t)
                                   for t in DEFAULT_TARGETS]
        report = run_paths(paths, rules)

    if args.json:
        print(report.to_json())
    else:
        print(report.format_human())
    print("LUXLINT " + json.dumps(report.summary(), sort_keys=True))
    if args.baseline:
        return _apply_baseline(report, args.baseline)
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
