#!/usr/bin/env python
"""memck_smoke: acceptance gate for the luxlint memory tier
(`make lint-memory`, wired into `make verify`).

Four claims, all asserted:

  1. **registry clean + fast** — pricing every traced registry target
     (LUX701-706) produces 0 findings and the proof phase (liveness
     walks + rule checks; executor staging and jit lowering are
     environment setup, untimed) fits the wall budget;
  2. **artifact parity** — the freshly derived ``memcap.v1`` footprint
     artifact has the same content-addressed id as the committed
     ``lux_tpu/analysis/memcap.json``: a footprint-changing edit fails
     verify until regenerated (``luxlint --memory --memcap-out
     lux_tpu/analysis/memcap.json``) — the offline half of the LUX706
     drift ratchet;
  3. **a seeded leak is caught** — the committed LUX702 fixture (a
     donation the lowered HLO never honors) must fail with exactly its
     rule, proving the tier distinguishes and not merely passes;
  4. **the budget has teeth at the front door** — under a one-byte HBM
     budget, a real HTTP query whose engine build the memcap.v1
     admission formula refuses is shed with a typed 503 +
     ``Retry-After``, and a direct pool exercise shows footprint-LRU
     eviction with zero recompiles on warm hits.

Exit status: 0 when all four hold. Emits one greppable
``MEMCKSMOKE {...}`` summary line (``memck_smoke.v1``, the merge_smoke
idiom).

Usage:
    python tools/memck_smoke.py               # default: 2s budget
    python tools/memck_smoke.py --budget-s 4
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import types
import urllib.error
import urllib.request

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ["JAX_PLATFORMS"] = "cpu"

from lux_tpu.utils.platform import virtual_cpu_flags  # noqa: E402

# Sharded registry targets trace against the 8-way virtual mesh the
# serve/exchange gates use; opt level 0 keeps lowering cheap.
os.environ["XLA_FLAGS"] = (virtual_cpu_flags(8)
                           + " --xla_backend_optimization_level=0")

from lux_tpu.analysis import memck  # noqa: E402

FIXTURE = os.path.join(_REPO, "tests", "mem_fixtures",
                       "lux702_unhonored_donation.py")


def _pool_residency_demo() -> dict:
    """Direct EnginePool exercise: footprint-LRU eviction under a tight
    budget, warm hits untouched (and recompile-free)."""
    from lux_tpu.serve.pool import EnginePool
    from lux_tpu.utils import flags

    pool = EnginePool(scope="memck-smoke")
    out = {"evicted": 0, "warm_hit": False, "recompiles": -1,
           "resident_bytes": -1}
    try:
        with flags.overrides({"LUX_HBM_BUDGET_BYTES": "1000"}):
            ev0 = pool.stats()["hbm_evictions"]
            a = pool.get(("a",), lambda: types.SimpleNamespace(),
                         footprint_bytes=600)
            out["warm_hit"] = pool.get(
                ("a",), lambda: types.SimpleNamespace(),
                footprint_bytes=600) is a
            pool.get(("b",), lambda: types.SimpleNamespace(),
                     footprint_bytes=600)     # does not fit: evicts a
            out["evicted"] = pool.stats()["hbm_evictions"] - ev0
            out["resident_bytes"] = pool.hbm_resident_bytes()
            out["recompiles"] = pool.stats()["recompiles"]
    finally:
        pool.close()
    out["ok"] = (out["evicted"] == 1 and out["warm_hit"]
                 and out["recompiles"] == 0
                 and out["resident_bytes"] == 600)
    return out


def _http_shed_demo() -> dict:
    """End-to-end: a one-byte budget makes the first engine build
    unadmittable, and the HTTP front end sheds the query with the typed
    503 + Retry-After instead of building (and OOMing) anyway."""
    from lux_tpu.graph import generate
    from lux_tpu.serve.http import serve_in_thread
    from lux_tpu.serve.session import Session

    out = {"status": None, "retry_after": None, "error": None}
    g = generate.gnp(96, 400, seed=11)
    # Env var, not flags.overrides: the overlay is context-local by
    # design (probe isolation) and the admission check runs on the
    # serve batcher thread, which must see the budget too.
    os.environ["LUX_HBM_BUDGET_BYTES"] = "1"
    try:
        session = Session(g, warm=False)
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/query",
                json.dumps({"app": "sssp", "start": 0}).encode(),
                {"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=60)
                out["error"] = "query was admitted under a 1 B budget"
            except urllib.error.HTTPError as e:
                out["status"] = e.code
                ra = e.headers.get("Retry-After")
                out["retry_after"] = float(ra) if ra else None
                body = json.loads(e.read() or b"{}")
                out["error"] = body.get("error")
        finally:
            server.shutdown()
            session.close()
    finally:
        del os.environ["LUX_HBM_BUDGET_BYTES"]
    out["ok"] = (out["status"] == 503
                 and (out["retry_after"] or 0) > 0)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="memck_smoke", description=__doc__)
    ap.add_argument("--budget-s", type=float, default=2.0,
                    help="wall budget for the registry proof phase")
    args = ap.parse_args(argv)

    report, art = memck.prove_registry()
    prove_s = report.summary()["elapsed_s"]

    for res in report.results:
        for f in res.findings:
            print(f.format())
        if res.error:
            print(f"{res.path}: {res.error}")

    clean = report.ok and not any(r.error for r in report.results)
    fast = prove_s <= args.budget_s

    committed_id = None
    parity = False
    try:
        committed = memck.load_memcap(memck.memcap_path())
        committed_id = committed["id"]
        parity = committed_id == art["id"]
    except Exception as e:  # missing or tampered artifact: loud, fatal
        print(f"memck_smoke: committed memcap.v1 unusable: {e!r}")

    fix_rules = []
    fixture_caught = False
    if os.path.exists(FIXTURE):
        fix_rep = memck.verify_fixture_paths([FIXTURE])
        fix_rules = sorted({f.rule for f in fix_rep.findings})
        fixture_caught = (not fix_rep.ok) and fix_rules == ["LUX702"]
    else:
        print(f"memck_smoke: missing fixture {FIXTURE}")

    pool_demo = _pool_residency_demo()
    shed_demo = _http_shed_demo()

    ok = (clean and fast and parity and fixture_caught
          and pool_demo["ok"] and shed_demo["ok"])
    summary = {
        "schema": "memck_smoke.v1",
        "targets": len(art["targets"]),
        "findings": len(report.findings),
        "errors": sum(1 for r in report.results if r.error),
        "prove_s": prove_s,
        "budget_s": args.budget_s,
        "clean": clean,
        "fast": fast,
        "artifact_id": art["id"],
        "committed_id": committed_id,
        "parity": parity,
        "fixture_rules": fix_rules,
        "fixture_caught": fixture_caught,
        "pool": pool_demo,
        "shed": shed_demo,
        "ok": ok,
    }
    print("MEMCKSMOKE " + json.dumps(summary, sort_keys=True))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
