#!/usr/bin/env python3
"""Grouped-tail smoke test (`make merge-smoke`).

Runs the full scheduler -> simulator -> fallback-kernel parity pipeline
on random skewed run sets whose sizes match the RMAT22 tail-edge
distribution recorded in PERF.md (per-source-block edge counts: mean
1243, p50 283, p99 ~17k, max ~79k, cv ~2.6 — drawn here from a capped
lognormal fit), then checks:

1. reference walk vs vectorized planner: identical routing planes;
2. planner plan executed by the jax.numpy fallback kernel: per-dst
   sums BITWISE equal to the scatter oracle on integral values;
3. achieved stream inflation below the acceptance bound (<1.5x mean
   across levels on the heavy-tailed synthetic);
4. end-to-end LUX_GROUPED_TAIL=1 PageRank parity through
   TiledPullExecutor on a small R-MAT graph.

Emits one line of JSON with the achieved inflation so CI logs are
greppable. Scale with LUX_SMOKE_EDGES (default ~1.2M reals).
"""

from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

INFLATION_BOUND = 1.5


def heavy_tail_sizes(rng, nsb):
    """Per-source-block tail-edge counts matching PERF.md's RMAT22
    stats (lognormal body, capped at the observed max)."""
    import numpy as np

    return np.minimum(
        rng.lognormal(6.4, 1.35, size=nsb).astype(np.int64) + 1, 79237)


def main() -> int:
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", os.environ["LUX_PLATFORM"])
    import jax.numpy as jnp
    import numpy as np

    from lux_tpu.ops import merge_tail_plan as mtp
    from lux_tpu.ops.merge_tail_kernel import (
        DeviceGroupedTail,
        grouped_tail_sums,
    )
    from lux_tpu.ops.merge_tail_ref import BLOCK, schedule_grouped

    from lux_tpu.utils import flags

    target_edges = flags.get_int("LUX_SMOKE_EDGES")
    rng = np.random.default_rng(0)

    # -- 1. scheduler vs planner on small random skewed run sets --------
    for seed in range(4):
        r2 = np.random.default_rng(seed)
        sizes = heavy_tail_sizes(r2, 6) // 64 + 1   # miniature skew
        runs = [np.sort(r2.integers(0, 200, size=s)) for s in sizes]
        ref_levels, _, _ = schedule_grouped(runs)
        d = np.concatenate([
            np.stack([run, np.full(len(run), i)], axis=1)
            for i, run in enumerate(runs)])
        d = d[np.lexsort((d[:, 1], d[:, 0]))]
        leaf = d[:, 1]
        pos = np.zeros(len(leaf), np.int64)
        for i in range(len(runs)):
            m = leaf == i
            pos[m] = np.arange(m.sum())
        levels, _, _, _ = mtp.plan_merge_network(
            d[:, 0], leaf, pos // BLOCK + np.cumsum(
                np.concatenate([[0], [(len(r) + BLOCK - 1) // BLOCK
                                      for r in runs[:-1]]]))[leaf],
            pos % BLOCK, len(runs))
        for lv, rlv in zip(levels, ref_levels):
            for key in ("arow", "brow", "codes", "nvalid", "mode"):
                if not np.array_equal(lv[key], rlv[key]):
                    print(f"FAIL: planner/reference drift seed={seed} "
                          f"key={key}")
                    return 1
    print("scheduler == planner on skewed run sets")

    # -- 2+3. heavy-tailed synthetic at scale: parity + inflation -------
    nsb = max(64, target_edges // 1243)
    sizes = heavy_tail_sizes(rng, nsb)
    m = int(sizes.sum())
    sb = np.repeat(np.arange(nsb), sizes)
    nv = 1 << 17
    dst = np.sort(rng.integers(0, nv, size=m))
    sb = sb[np.lexsort((sb, dst))]
    lane = rng.integers(0, BLOCK, size=m)
    row_ptr = np.searchsorted(dst, np.arange(nv + 1))

    t0 = time.perf_counter()
    plan = mtp.plan_grouped_tail(sb, lane, row_ptr)
    plan_secs = time.perf_counter() - t0

    gt = DeviceGroupedTail.build(plan)
    x2d = rng.integers(-30, 30, size=(nsb, BLOCK)).astype(np.float32)
    got = np.asarray(jax.jit(grouped_tail_sums)(jnp.asarray(x2d), gt))
    want = np.zeros(nv, np.float64)
    np.add.at(want, dst, x2d[sb, lane].astype(np.float64))
    if not np.array_equal(got, want.astype(np.float32)):
        print("FAIL: fallback-kernel sums differ from oracle")
        return 1
    print(f"fallback kernel bitwise parity on {m} reals")

    inflation = plan.stats["mean_inflation"]
    if inflation >= INFLATION_BOUND:
        print(f"FAIL: mean inflation {inflation:.3f} >= {INFLATION_BOUND}")
        return 1

    # -- 4. end-to-end executor parity ----------------------------------
    from lux_tpu.engine.tiled import TiledPullExecutor
    from lux_tpu.graph.generate import rmat
    from lux_tpu.models.pagerank import PageRank

    g = rmat(int(os.environ.get("LUX_SMOKE_SCALE", "11")), 12, seed=1)
    ex0 = TiledPullExecutor(g, PageRank(), chunk_strips=16, chunk_tail=64)
    os.environ["LUX_GROUPED_TAIL"] = "1"
    try:
        ex1 = TiledPullExecutor(
            g, PageRank(), chunk_strips=16, chunk_tail=64)
    finally:
        del os.environ["LUX_GROUPED_TAIL"]
    v0 = np.asarray(ex0.run(6))
    v1 = np.asarray(ex1.run(6))
    if not np.allclose(v0, v1, rtol=1e-5, atol=1e-8):
        print(f"FAIL: pagerank drift {np.abs(v0 - v1).max():.3e}")
        return 1
    print("LUX_GROUPED_TAIL=1 pagerank parity OK")

    print(json.dumps({
        "merge_smoke": "ok",
        "edges": m,
        "levels": plan.n_levels,
        "mean_inflation": round(inflation, 4),
        "max_level_inflation": round(
            plan.stats["max_level_inflation"], 4),
        "copy_rows": int(plan.stats["copy_rows"]),
        "merge_rows": int(plan.stats["merge_rows"]),
        "plan_seconds": round(plan_secs, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
