#!/usr/bin/env python3
"""Round-2 microbenches: tail-select variants + int4 strips (v5e).

Measurement discipline per PERF.md: hard syncs, measured op carried
through a fori_loop via a data dependency, two trip counts (3/13) to
subtract fixed dispatch cost. All device arrays are jit ARGUMENTS
(closed-over arrays bake into the remote-compile request as constants —
tens of MB per compile through the tunnel). Trip count is traced, so
each variant compiles once.
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

ONLY = set(sys.argv[1:])  # run a subset: names as args


def timed(name, fn, *args, per=None):
    if ONLY and name.split()[0] not in ONLY:
        return
    f = jax.jit(fn)
    t0 = time.perf_counter()
    hard_sync(f(jnp.int32(3), *args))
    print(f"# {name}: compile+first {time.perf_counter()-t0:.1f}s",
          file=sys.stderr, flush=True)
    ts = {}
    for n in (3, 13):
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            hard_sync(f(jnp.int32(n), *args))
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    dt = (ts[13] - ts[3]) / 10
    unit = f"  ({dt/per*1e9:.3f} ns/item)" if per else ""
    print(f"{name:42s} {dt*1e3:8.2f} ms{unit}", flush=True)
    return dt


NVB = 32768          # rmat22-sized table: (32768,128) f32 = 16 MB
C = 1 << 17
K = 60
M = C * K

rng = np.random.default_rng(0)
x2d = jnp.asarray(rng.standard_normal((NVB, 128), dtype=np.float32))
sb = jnp.asarray(rng.integers(0, NVB, (K, C), dtype=np.int32))
lane = jnp.asarray(rng.integers(0, 128, (K, C), dtype=np.int8))

iota = jnp.arange(128, dtype=jnp.int32)


def loop(n, body, x, *chunks):
    def outer(i, acc):
        def inner(c, a):
            return a + body(x + a[0] * 1e-30, tuple(t[c] for t in chunks))
        return jax.lax.fori_loop(0, K, inner, acc)
    return jax.lax.fori_loop(0, n, outer, jnp.zeros((C,), jnp.float32))


def v_where(x, ch):
    s, l = ch
    rows = x[s]
    return jnp.where(
        l.astype(jnp.int32)[:, None] == iota[None, :], rows, 0.0
    ).sum(axis=1)


def v_take_along(x, ch):
    s, l = ch
    rows = x[s]
    return jnp.take_along_axis(rows, l.astype(jnp.int32)[:, None], axis=1)[:, 0]


def v_bare(x, ch):
    s, l = ch
    return x[s].sum(axis=1)


print(f"tail variants over {M/1e6:.1f}M edges, table 16MB:", flush=True)
timed("where+sum (current)",
      lambda n, x, s, l: loop(n, v_where, x, s, l), x2d, sb, lane, per=M)
timed("take_along_axis",
      lambda n, x, s, l: loop(n, v_take_along, x, s, l), x2d, sb, lane, per=M)
timed("bare gather+rowsum (floor)",
      lambda n, x, s, l: loop(n, v_bare, x, s, l), x2d, sb, lane, per=M)

# ---- strip contraction dtype variants --------------------------------
CS = 1 << 15
KS = 24
T = CS * KS
st8 = jnp.asarray(rng.integers(0, 3, (KS, CS, 8, 128), dtype=np.int8))
cols = jnp.asarray(rng.integers(0, NVB, (KS, CS), dtype=np.int32))


def sloop(n, x, strips, co):
    def outer(i, acc):
        def inner(c, a):
            xb = (x + a[0, 0] * 1e-30)[co[c]]
            return a + (strips[c].astype(jnp.float32) * xb[:, None, :]).sum(-1)
        return jax.lax.fori_loop(0, KS, inner, acc)
    return jax.lax.fori_loop(0, n, outer, jnp.zeros((CS, 8), jnp.float32))


print(f"\nstrip contraction over {T/1e6:.1f}M strips (8,128):", flush=True)
timed("int8 strips (current)", sloop, x2d, st8, cols, per=T)
timed("int4 strips", sloop, x2d, st8.astype(jnp.int4), cols, per=T)
