#!/usr/bin/env python3
"""Telemetry smoke test (`make obs-smoke`): run PageRank with LUX_METRICS
and LUX_TRACE enabled on a small R-MAT graph and validate both outputs
parse — the metrics dump has one record per iteration with monotone
cumulative time and a compile/execute split, and the trace is valid
JSON-lines with balanced B/E span pairs.

Scale with LUX_SMOKE_SCALE (default 10; acceptance-criteria runs use 14).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")
    ni = flags.get_int("LUX_SMOKE_ITERS")

    # Force CPU before any backend initializes (the environment's
    # sitecustomize may register a TPU plugin).
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.graph import generate, write_lux
    from lux_tpu.models import pagerank

    with tempfile.TemporaryDirectory() as td:
        gpath = os.path.join(td, f"rmat{scale}.lux")
        mpath = os.path.join(td, "metrics.jsonl")
        tpath = os.path.join(td, "trace.jsonl")
        write_lux(gpath, generate.rmat(scale, 8, seed=1))

        rc = pagerank.main([
            "-file", gpath, "-ni", str(ni),
            "-metrics", mpath, "-trace", tpath,
        ])
        if rc != 0:
            print(f"FAIL: pagerank exited {rc}")
            return 1

        # -- metrics dump ------------------------------------------------
        with open(mpath) as f:
            runs = [json.loads(line) for line in f if line.strip()]
        if not runs:
            print("FAIL: metrics dump is empty")
            return 1
        run = runs[-1]
        problems = []
        if run.get("schema") != "lux.run_telemetry.v1":
            problems.append(f"bad schema: {run.get('schema')!r}")
        if run.get("num_iters") != ni:
            problems.append(f"num_iters {run.get('num_iters')} != {ni}")
        iterations = run.get("iterations", [])
        if len(iterations) != ni:
            problems.append(f"{len(iterations)} iteration records != {ni}")
        cum = [r["t_cum_s"] for r in iterations]
        if any(b < a for a, b in zip(cum, cum[1:])):
            problems.append("t_cum_s is not monotone")
        if run.get("compile_s", -1) < 0:
            problems.append("missing compile_s")
        if run.get("execute_s", 0) <= 0:
            problems.append("execute_s not positive")
        if "metrics" not in run:
            problems.append("missing metrics registry snapshot")

        # -- trace -------------------------------------------------------
        with open(tpath) as f:
            events = [json.loads(line) for line in f if line.strip()]
        if not events:
            problems.append("trace is empty")
        depth = 0
        for ev in events:
            if ev.get("ph") == "B":
                depth += 1
            elif ev.get("ph") == "E":
                depth -= 1
                if depth < 0:
                    problems.append("trace has E before B")
                    break
        if depth > 0:
            problems.append(f"trace has {depth} unclosed B span(s)")

        if problems:
            for p in problems:
                print(f"FAIL: {p}")
            return 1
        print(
            f"OK: {ni} iteration records "
            f"(compile {run['compile_s']:.3f}s, "
            f"execute {run['execute_s']:.4f}s, "
            f"gteps {run['gteps']:.4f}); "
            f"trace: {len(events)} events, B/E balanced"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
