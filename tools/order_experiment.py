#!/usr/bin/env python3
"""Host-only vertex-ordering experiments scored by modeled iteration time.

Model (ns, calibrated on round-1 v5e phase measurements at (8,2)):
    t = 4.9*strips + 2.55*tail_edges + 6*strip_rows + 3*nv + fixed
Round-1 measured 115 ms/iter; model gives 119.7 — good enough to rank
orderings without a TPU in the loop.
"""
import sys, os, time
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from lux_tpu.graph import read_lux

BLOCK = 128


def coverage(s, d, nv, r=8, thr=2):
    nvb = (nv + 127) // 128
    sid = (d // r).astype(np.int64) * nvb + (s >> 7)
    us, cs = np.unique(sid, return_counts=True)
    m = cs >= thr
    strips = int(m.sum())
    cov_edges = int(cs[m].sum())
    ne = len(s)
    tail = ne - cov_edges
    t_model = (4.9 * strips + 2.55 * tail + 6 * (nv // r) + 3 * nv) / 1e6
    return cov_edges / ne, strips, tail, t_model


def score(name, rank, g):
    s = rank[g.col_src]
    d = rank[g.col_dst]
    cov, strips, tail, t = coverage(s, d, g.nv)
    print(f"{name:34s} cov={cov:6.1%} strips={strips/1e6:5.2f}M "
          f"tail={tail/1e6:5.1f}M  t_model={t:6.1f} ms", flush=True)
    return t


def main():
    g = read_lux(sys.argv[1] if len(sys.argv) > 1 else
                 ".bench_cache/rmat22_16.lux")
    nv = g.nv
    deg = g.in_degrees + g.out_degrees

    # baseline: degree sort
    order0 = np.argsort(-deg, kind="stable").astype(np.int32)
    rank0 = np.empty(nv, np.int32); rank0[order0] = np.arange(nv, dtype=np.int32)
    score("degree (baseline)", rank0, g)

    # --- dominant-dst-row clustering on top of degree sort -------------
    # Hubs (top block of the degree order) keep their slots; every other
    # source is keyed by the smallest dst-row (in degree order) it points
    # at, so single-edge sources aiming at the same row share a block.
    s0 = rank0[g.col_src]; d0 = rank0[g.col_dst]
    for r in (8,):
        for hub_frac in (0.02, 0.05, 0.10, 0.25):
            nhub = int(nv * hub_frac)
            t0 = time.time()
            drow = d0 // r
            # min dst-row per src (sources with no out-edges get a big key)
            key = np.full(nv, np.int64(nv), np.int64)
            np.minimum.at(key, s0, drow)
            is_hub = rank0 < nhub  # internal position < nhub
            # order: hubs first (by degree), then others by (min-row, deg)
            rest = np.arange(nv, dtype=np.int64)[~is_hub[np.arange(nv)]]
            # sort rest by (key, rank0) — pack into one int64 for radix
            packed = key[rest] * nv + rank0[rest]
            rest = rest[np.argsort(packed, kind="stable")]
            hubs = order0[:nhub]
            order1 = np.concatenate([hubs, rest.astype(np.int32)])
            rank1 = np.empty(nv, np.int32)
            rank1[order1] = np.arange(nv, dtype=np.int32)
            score(f"minrow r={r} hubs={hub_frac:.0%} "
                  f"({time.time()-t0:.0f}s)", rank1, g)

    # --- iterate: recompute min-row under the improved order -----------
    # (best hub_frac from above pass, one refinement round)
    nhub = int(nv * 0.05)
    rank = rank0
    for it in range(3):
        sL = rank[g.col_src]; dL = rank[g.col_dst]
        drow = dL // 8
        key = np.full(nv, np.int64(nv), np.int64)
        np.minimum.at(key, sL, drow)
        is_hub_pos = rank < nhub
        rest = np.arange(nv, dtype=np.int64)[~is_hub_pos]
        packed = key[rest] * nv + rank[rest]
        rest = rest[np.argsort(packed, kind="stable")]
        hubs = np.arange(nv, dtype=np.int32)[is_hub_pos][
            np.argsort(rank[is_hub_pos], kind="stable")]
        order = np.concatenate([hubs, rest.astype(np.int32)])
        rank = np.empty(nv, np.int32)
        rank[order] = np.arange(nv, dtype=np.int32)
        score(f"minrow iter{it+1} hubs=5%", rank, g)


if __name__ == "__main__":
    main()
