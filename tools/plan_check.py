#!/usr/bin/env python
"""plan_check: end-to-end proof the grouped-plan verifier earns its keep.

Synthesizes a heavy-tail run set at serving scale (>= 1M reals by
default), plans it with ``plan_grouped_tail``, saves the artifact, and
then demonstrates both halves of the LUX2xx contract:

  1. the shipped planner's output verifies clean, and fast — the wall
     budget below is asserted, because a verifier too slow to sit in a
     load path is a verifier nobody runs;
  2. a byte-corrupted copy of the same artifact is rejected.

Exit status: 0 when both hold. Emits one greppable ``PLANCHECK {...}``
summary line (the merge_smoke idiom).

Usage:
    python tools/plan_check.py                # default: ~1.2M reals
    python tools/plan_check.py --reals 200000 --budget-s 2.0
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

from lux_tpu.analysis import planck  # noqa: E402
from lux_tpu.ops import merge_tail_plan as mtp  # noqa: E402


def synth_tail(reals: int, seed: int = 0):
    """Heavy-tail run set in the merge_smoke shape: lognormal run sizes
    (clipped at the PR-3 smoke ceiling), shuffled interleave, uniform
    lanes, sorted destination rows."""
    rng = np.random.default_rng(seed)
    sizes = np.empty(0, np.int64)
    while int(sizes.sum()) < reals:
        more = np.minimum(
            rng.lognormal(6.4, 1.35, size=256).astype(np.int64) + 1, 79237)
        sizes = np.concatenate([sizes, more])
    m = int(sizes.sum())
    sb = np.repeat(np.arange(sizes.size), sizes)
    rng.shuffle(sb)
    lane = rng.integers(0, 128, size=m)
    nv = max(m // 300, 64)
    dst = np.sort(rng.integers(0, nv, size=m))
    row_ptr = np.searchsorted(dst, np.arange(nv + 1))
    return sb, lane, row_ptr, m


def corrupt(src: str, dst: str) -> None:
    """A plausible on-disk corruption: a stale partial rewrite that bumps
    one level boundary and inflates one row's lane count — breaks
    conservation (LUX202) and the code-plane contract (LUX203) without
    touching array shapes, so only a semantic verifier catches it."""
    shutil.copytree(src, dst)
    lp = np.load(os.path.join(dst, "level_ptr.npy"))
    lp[1] += 8
    np.save(os.path.join(dst, "level_ptr.npy"), lp)
    nv = np.load(os.path.join(dst, "nvalid.npy"))
    nv[0] = 200
    np.save(os.path.join(dst, "nvalid.npy"), nv)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="plan_check", description=__doc__)
    ap.add_argument("--reals", type=int, default=1_000_000,
                    help="minimum reals in the synthetic tail")
    ap.add_argument("--budget-s", type=float, default=2.0,
                    help="wall budget for verifying the saved artifact")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--keep", default="",
                    help="keep artifacts under this dir instead of a tmpdir")
    args = ap.parse_args(argv)

    sb, lane, row_ptr, m = synth_tail(args.reals, args.seed)
    t0 = time.perf_counter()
    plan = mtp.plan_grouped_tail(sb, lane, row_ptr)
    plan_s = time.perf_counter() - t0

    root = args.keep or tempfile.mkdtemp(prefix="lux_plan_check_")
    good = os.path.join(root, "plan")
    bad = os.path.join(root, "plan_corrupt")
    mtp.save_grouped_plan(good, plan)
    corrupt(good, bad)

    t0 = time.perf_counter()
    rep_good = planck.verify_plan_dirs([good])
    verify_s = time.perf_counter() - t0
    rep_bad = planck.verify_plan_dirs([bad])

    for res in rep_good.results:
        for f in res.findings:
            print(f.format())
        if res.error:
            print(f"{res.path}: {res.error}")

    clean = rep_good.ok
    fast = verify_s <= args.budget_s
    caught = not rep_bad.ok
    ok = clean and fast and caught
    summary = {
        "reals": m,
        "levels": int(plan.n_levels),
        "rows": int(plan.level_ptr[-1]),
        "plan_s": round(plan_s, 3),
        "verify_s": round(verify_s, 3),
        "budget_s": args.budget_s,
        "clean": clean,
        "fast": fast,
        "corrupt_rules": sorted({f.rule for f in rep_bad.findings}),
        "corrupt_caught": caught,
        "ok": ok,
    }
    print("PLANCHECK " + json.dumps(summary, sort_keys=True))
    if not args.keep:
        shutil.rmtree(root, ignore_errors=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
