#!/usr/bin/env python3
"""Probe 3: is the tail row gather bandwidth-bound (halves with bf16
rows) or per-row latency-bound (doesn't)?

Layout under test: value table as (nvb*2, 128) bf16 where row 2b holds
hi[64 srcs]||lo[64 srcs]... actually packed as one row per 64-src
half-block: row h = [hi(v_0..v_63) || lo(v_0..v_63)] — per tail edge one
256 B row gather + two lane selects (lane, lane+64) reconstructs the f32
value to ~2^-16 rel.
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

ONLY = set(sys.argv[1:])


def timed(name, fn, *args, per=None):
    if ONLY and name.split()[0] not in ONLY:
        return
    f = jax.jit(fn)
    try:
        t0 = time.perf_counter()
        hard_sync(f(jnp.int32(3), *args))
        print(f"# {name}: compile+first {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"{name:44s} FAILED: {type(e).__name__}: {str(e)[:120]}",
              flush=True)
        return None
    ts = {}
    for n in (3, 13):
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            hard_sync(f(jnp.int32(n), *args))
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    dt = (ts[13] - ts[3]) / 10
    unit = f"  ({dt/per*1e9:.3f} ns/item)" if per else ""
    print(f"{name:44s} {dt*1e3:8.2f} ms{unit}", flush=True)
    return dt


rng = np.random.default_rng(0)
NVB = 32768          # (32768,128) f32 = 16 MB table (RMAT22 shape)
C = 1 << 17
K = 60               # 7.9M edges per call... use 60 chunks like r2 probe
M = C * K

xf32 = jnp.asarray(rng.standard_normal((NVB, 128), dtype=np.float32))
# hi/lo packed: table of 64-src half-rows, twice as many rows, bf16
xbf = jnp.asarray(
    rng.standard_normal((NVB * 2, 128), dtype=np.float32)
).astype(jnp.bfloat16)
sb32 = jnp.asarray(rng.integers(0, NVB, (K, C), dtype=np.int32))
sb64 = jnp.asarray(rng.integers(0, NVB * 2, (K, C), dtype=np.int32))
lane = jnp.asarray(rng.integers(0, 64, (K, C), dtype=np.int8))
iota = jnp.arange(128, dtype=jnp.int32)


def loop(n, body, x, *chunks):
    def outer(i, acc):
        def inner(c, a):
            return a + body(x + a[0].astype(x.dtype) * 1e-30,
                            tuple(t[c] for t in chunks))
        return jax.lax.fori_loop(0, K, inner, acc)
    return jax.lax.fori_loop(0, n, outer, jnp.zeros((C,), jnp.float32))


def v_bare_f32(x, ch):
    (s,) = ch
    return x[s].sum(axis=1)


def v_bare_bf16(x, ch):
    (s,) = ch
    return x[s].astype(jnp.float32).sum(axis=1)


def v_hilo(x, ch):
    s, l = ch
    rows = x[s]                      # (C,128) bf16
    li = l.astype(jnp.int32)
    hi = jnp.where(li[:, None] == iota[None, :], rows, 0).sum(axis=1)
    lo = jnp.where((li[:, None] + 64) == iota[None, :], rows, 0).sum(axis=1)
    return hi.astype(jnp.float32) + lo.astype(jnp.float32)


def v_f32_select(x, ch):
    s, l = ch
    rows = x[s]
    li = l.astype(jnp.int32)
    return jnp.where(li[:, None] == iota[None, :], rows, 0.0).sum(axis=1)


print(f"tail gather variants over {M/1e6:.1f}M edges:", flush=True)
timed("bare f32 512B rows (r2 floor)",
      lambda n, x, s: loop(n, v_bare_f32, x, s), xf32, sb32, per=M)
timed("bare bf16 256B rows",
      lambda n, x, s: loop(n, v_bare_bf16, x, s), xbf, sb64, per=M)
timed("f32 gather+select (current tail)",
      lambda n, x, s, l: loop(n, v_f32_select, x, s, l), xf32, sb32, lane,
      per=M)
timed("bf16 hilo gather+2select",
      lambda n, x, s, l: loop(n, v_hilo, x, s, l), xbf, sb64, lane, per=M)
