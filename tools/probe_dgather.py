#!/usr/bin/env python3
"""Probe: does Mosaic's tpu.dynamic_gather (jax 0.9) work from Pallas on
this backend, at which shapes, and at what rate?

take_along_axis(x, idx, axis) with x.shape == idx.shape == out.shape and
x 2-D lowers to tpu.dynamic_gather inside a Pallas TPU kernel
(jax/_src/pallas/mosaic/lowering.py:2464-2525). axis=1 is the per-sublane
lane gather (the tail's lane-select); axis=0 is the per-lane cross-sublane
gather (the permutation primitive). Round 2 (jax 0.8) crashed on >1-vreg
operands; jax 0.9 re-probe.
"""
import sys, os, time, functools
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

ONLY = set(sys.argv[1:])


def kernel_ta(axis, x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=axis)


def make_ta(S, L, axis, reps):
    """One pallas_call gathering a (S, L) block; grid over reps blocks."""
    f = pl.pallas_call(
        functools.partial(kernel_ta, axis),
        out_shape=jax.ShapeDtypeStruct((reps * S, L), jnp.float32),
        grid=(reps,),
        in_specs=[
            pl.BlockSpec((S, L), lambda i: (i, 0)),
            pl.BlockSpec((S, L), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((S, L), lambda i: (i, 0)),
    )
    return f


def timed(name, fn, *args, per=None):
    if ONLY and name.split()[0] not in ONLY:
        return
    f = jax.jit(fn)
    try:
        t0 = time.perf_counter()
        hard_sync(f(jnp.int32(3), *args))
        print(f"# {name}: compile+first {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"{name:46s} FAILED: {type(e).__name__}: {str(e)[:140]}",
              flush=True)
        return None
    ts = {}
    for n in (3, 13):
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            hard_sync(f(jnp.int32(n), *args))
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    dt = (ts[13] - ts[3]) / 10
    unit = f"  ({dt/per*1e9:.3f} ns/item)" if per else ""
    print(f"{name:46s} {dt*1e3:8.2f} ms{unit}", flush=True)
    return dt


def loop(n, f, x, idx):
    def body(i, acc):
        return acc + f(x + acc[0, 0] * 1e-30, idx)
    return jax.lax.fori_loop(0, n, body, jnp.zeros(x.shape, jnp.float32))


rng = np.random.default_rng(0)

for (S, L, axis, reps) in [
    (8, 128, 1, 1), (8, 128, 0, 1),
    (512, 128, 1, 1), (512, 128, 0, 1),
    (4096, 128, 1, 16), (4096, 128, 0, 16),
    (8192, 128, 0, 32),
]:
    n_el = reps * S * L
    x = jnp.asarray(rng.standard_normal((reps * S, L), dtype=np.float32))
    hi = S if axis == 0 else L
    idx = jnp.asarray(rng.integers(0, hi, (reps * S, L), dtype=np.int32))
    f = make_ta(S, L, axis, reps)
    timed(f"ta axis={axis} ({S},{L})x{reps}",
          lambda n, x, i, f=f: loop(n, f, x, i), x, idx, per=n_el)
