#!/usr/bin/env python3
"""Probe 2: rates of the primitives a grouped-tail + merge-network
permutation would compose (see PERF.md round-3 section).

- lane gather (tpu.dynamic_gather axis=1) at 34M-element scale
- (8,128) sublane gather (axis=0) at scale
- merge-level prototype: out[i,j] = cand[i, s[i,j], l[i,j]] via 4
  lane-gathers + masked sum (one level of a 4-candidate merge network)
- XLA row gather of ~300K padded rows from a ~150 MB table (the
  inter-tile row-move stage)
"""
import sys, os, time, functools
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

ONLY = set(sys.argv[1:])


def timed(name, fn, *args, per=None):
    if ONLY and name.split()[0] not in ONLY:
        return
    f = jax.jit(fn)
    try:
        t0 = time.perf_counter()
        hard_sync(f(jnp.int32(3), *args))
        print(f"# {name}: compile+first {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"{name:44s} FAILED: {type(e).__name__}: {str(e)[:120]}",
              flush=True)
        return None
    ts = {}
    for n in (3, 13):
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            hard_sync(f(jnp.int32(n), *args))
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    dt = (ts[13] - ts[3]) / 10
    unit = f"  ({dt/per*1e9:.3f} ns/item)" if per else ""
    print(f"{name:44s} {dt*1e3:8.2f} ms{unit}", flush=True)
    return dt


rng = np.random.default_rng(0)

# ---- lane gather at scale: (S,128) blocks over a big stream ----------
S, NB = 4096, 64                      # 33.5M elements, 134 MB
M = S * NB * 128


def k_lane(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=1)


lane_call = pl.pallas_call(
    k_lane,
    out_shape=jax.ShapeDtypeStruct((S * NB, 128), jnp.float32),
    grid=(NB,),
    in_specs=[pl.BlockSpec((S, 128), lambda i: (i, 0)),
              pl.BlockSpec((S, 128), lambda i: (i, 0))],
    out_specs=pl.BlockSpec((S, 128), lambda i: (i, 0)),
)

x = jnp.asarray(rng.standard_normal((S * NB, 128), dtype=np.float32))
li32 = jnp.asarray(rng.integers(0, 128, (S * NB, 128), dtype=np.int32))
li8 = li32.astype(jnp.int8)


def loop(n, f, x, *rest):
    def body(i, acc):
        return acc + f(x + acc[0, 0] * 1e-30, *rest)
    return jax.lax.fori_loop(0, n, body, jnp.zeros((S * NB, 128), jnp.float32))


timed("lane-gather 33.5M i32", lambda n, x, i: loop(n, lane_call, x, i),
      x, li32, per=M)


def k_lane8(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(
        x_ref[:], i_ref[:].astype(jnp.int32), axis=1)


lane8_call = pl.pallas_call(
    k_lane8,
    out_shape=jax.ShapeDtypeStruct((S * NB, 128), jnp.float32),
    grid=(NB,),
    in_specs=[pl.BlockSpec((S, 128), lambda i: (i, 0)),
              pl.BlockSpec((S, 128), lambda i: (i, 0))],
    out_specs=pl.BlockSpec((S, 128), lambda i: (i, 0)),
)
timed("lane-gather 33.5M i8-idx", lambda n, x, i: loop(n, lane8_call, x, i),
      x, li8, per=M)

# ---- sublane gather within (8,128) at scale --------------------------


def k_sub(x_ref, i_ref, o_ref):
    o_ref[:] = jnp.take_along_axis(x_ref[:], i_ref[:], axis=0)


SB = 512   # rows per block = 64 sub-tiles of 8... axis0 only allows S=8
sub_call = pl.pallas_call(
    k_sub,
    out_shape=jax.ShapeDtypeStruct((S * NB, 128), jnp.float32),
    grid=(S * NB // 8,),
    in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0)),
              pl.BlockSpec((8, 128), lambda i: (i, 0))],
    out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
)
si32 = jnp.asarray(rng.integers(0, 8, (S * NB, 128), dtype=np.int32))
timed("sublane-gather(8) 33.5M", lambda n, x, i: loop(n, sub_call, x, i),
      x, si32, per=M)

# ---- merge-level prototype: 4 candidates per out row ------------------
R = 65536                              # out rows; cand = (R,4,128) 134MB


def k_merge(c_ref, l_ref, s_ref, o_ref):
    c = c_ref[:]                       # (Rb, 4, 128)
    l = l_ref[:]                       # (Rb, 128) int32 lane idx
    s = s_ref[:]                       # (Rb, 128) int32 cand idx
    acc = jnp.zeros(l.shape, jnp.float32)
    for k in range(4):
        g = jnp.take_along_axis(c[:, k, :], l, axis=1)
        acc = acc + jnp.where(s == k, g, 0.0)
    o_ref[:] = acc


RB = 2048
merge_call = pl.pallas_call(
    k_merge,
    out_shape=jax.ShapeDtypeStruct((R, 128), jnp.float32),
    grid=(R // RB,),
    in_specs=[pl.BlockSpec((RB, 4, 128), lambda i: (i, 0, 0)),
              pl.BlockSpec((RB, 128), lambda i: (i, 0)),
              pl.BlockSpec((RB, 128), lambda i: (i, 0))],
    out_specs=pl.BlockSpec((RB, 128), lambda i: (i, 0)),
)
cand = jnp.asarray(rng.standard_normal((R, 4, 128), dtype=np.float32))
lm = jnp.asarray(rng.integers(0, 128, (R, 128), dtype=np.int32))
sm = jnp.asarray(rng.integers(0, 4, (R, 128), dtype=np.int32))


def loopm(n, c, l, s):
    def body(i, acc):
        return acc + merge_call(c + acc[0, 0] * 1e-30, l, s)
    return jax.lax.fori_loop(0, n, body, jnp.zeros((R, 128), jnp.float32))


timed(f"merge-level {R*128/1e6:.1f}M out (4-cand)", loopm, cand, lm, sm,
      per=R * 128)

# ---- XLA row gather: 300K rows from 150 MB table ---------------------
TR = 300_000
big = jnp.asarray(rng.standard_normal((294912, 128), dtype=np.float32))
ridx = jnp.asarray(rng.integers(0, 294912, TR, dtype=np.int32))


def loopg(n, t, i):
    def body(k, acc):
        return acc + (t + acc[0] * 1e-30)[i].sum(0)
    return jax.lax.fori_loop(0, n, body, jnp.zeros((128,), jnp.float32))


timed("row-gather 300K from 150MB", loopg, big, ridx, per=TR)

# Same but table segmented under the 48MB cliff (gather from slices)
def loopg_seg(n, t, i):
    nseg = 4
    seg = 294912 // nseg
    def body(k, acc):
        tt = t + acc[0] * 1e-30
        out = jnp.zeros((128,), jnp.float32)
        for s_ in range(nseg):
            sl = jax.lax.dynamic_slice(tt, (s_ * seg, 0), (seg, 128))
            loc = jnp.clip(i - s_ * seg, 0, seg - 1)
            mask = ((i >= s_ * seg) & (i < (s_ + 1) * seg))
            out = out + jnp.where(mask[:, None], sl[loc], 0.0).sum(0)
        return acc + out
    return jax.lax.fori_loop(0, n, body, jnp.zeros((128,), jnp.float32))


timed("row-gather 300K segmented(4x)", loopg_seg, big, ridx, per=TR)
