#!/usr/bin/env python3
"""Probe the merge-level kernel's Mosaic requirements before building
the merge-tail network: (a) repeat-by-2 along sublanes inside a kernel
(broadcast+reshape and jnp.repeat lowerings), (b) PrefetchScalarGridSpec
with per-block dynamic input offsets, (c) the full 2-cand merge level at
scale, (d) correctness vs numpy."""
import sys, os, time, functools
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

rng = np.random.default_rng(0)


def k_merge(aoff_ref, boff_ref, a_ref, b_ref, i_ref, o_ref):
    a = a_ref[...]                       # (8, 128)
    b = b_ref[...]
    arep = jnp.broadcast_to(a[:, None, :], (8, 2, 128)).reshape(16, 128)
    brep = jnp.broadcast_to(b[:, None, :], (8, 2, 128)).reshape(16, 128)
    v = i_ref[...].astype(jnp.int32)   # int8 bitwise ops don't lower
    lane = v & 127
    ga = jnp.take_along_axis(arep, lane, axis=1)
    gb = jnp.take_along_axis(brep, lane, axis=1)
    o_ref[...] = jnp.where(v >= 0, ga, gb)


def make_merge(G, R_in):
    """G out blocks of (16,128); A/B windows of (8,128) at per-block
    prefetched 8-row-block offsets into one (R_in,128) stream."""
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(G,),
        in_specs=[
            pl.BlockSpec((8, 128), lambda g, aoff, boff: (aoff[g], 0)),
            pl.BlockSpec((8, 128), lambda g, aoff, boff: (boff[g], 0)),
            pl.BlockSpec((16, 128), lambda g, aoff, boff: (g, 0)),
        ],
        out_specs=pl.BlockSpec((16, 128), lambda g, aoff, boff: (g, 0)),
    )
    return pl.pallas_call(
        k_merge,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((G * 16, 128), jnp.float32),
    )


# -- correctness on a tiny case ----------------------------------------
G = 4
R_in = 64
stream = rng.standard_normal((R_in, 128), dtype=np.float32)
aoff = rng.integers(0, R_in // 8 - 1, G).astype(np.int32)
boff = rng.integers(0, R_in // 8 - 1, G).astype(np.int32)
idx = rng.integers(-128, 128, (G * 16, 128)).astype(np.int8)

f = jax.jit(make_merge(G, R_in))
try:
    got = np.asarray(hard_sync(f(
        jnp.asarray(aoff), jnp.asarray(boff),
        jnp.asarray(stream), jnp.asarray(stream), jnp.asarray(idx),
    )))
except Exception as e:
    print("merge kernel FAILED:", type(e).__name__, str(e)[:300])
    sys.exit(1)

want = np.empty_like(got)
for g in range(G):
    aw = stream[8 * aoff[g] : 8 * aoff[g] + 8]
    bw = stream[8 * boff[g] : 8 * boff[g] + 8]
    for i in range(16):
        for j in range(128):
            v = int(idx[16 * g + i, j])
            lane = v & 127
            src = aw if v >= 0 else bw
            want[16 * g + i, j] = src[i // 2, lane]
np.testing.assert_allclose(got, want)
print("merge kernel CORRECT on tiny case", flush=True)

# -- rate at scale ------------------------------------------------------
G = 1 << 17          # 2M out rows
R_in = G * 8 + 8
stream_b = jnp.asarray(rng.standard_normal((R_in, 128), dtype=np.float32))
aoff_b = jnp.asarray(
    rng.integers(0, R_in // 8 - 1, G, dtype=np.int64).astype(np.int32))
boff_b = jnp.asarray(
    rng.integers(0, R_in // 8 - 1, G, dtype=np.int64).astype(np.int32))
idx_b = jnp.asarray(rng.integers(-128, 128, (G * 16, 128)).astype(np.int8))
fb = jax.jit(make_merge(G, R_in))
M = G * 16 * 128

t0 = time.perf_counter()
hard_sync(fb(aoff_b, boff_b, stream_b, stream_b, idx_b))
print(f"# compile+first {time.perf_counter()-t0:.1f}s", file=sys.stderr)
for _ in range(3):
    t0 = time.perf_counter()
    hard_sync(fb(aoff_b, boff_b, stream_b, stream_b, idx_b))
    dt = time.perf_counter() - t0
    print(f"merge level {M/1e6:.0f}M slots: {dt*1e3:.2f} ms "
          f"({dt/M*1e9:.3f} ns/slot)", flush=True)
