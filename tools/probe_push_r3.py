#!/usr/bin/env python3
"""Probe: why the SSSP blocked-dense phases run above their byte model
(PERF.md round-2 #5), and what the fixes buy.

- load: uint32 row-gather+select+relax (current) vs f32 sign-bit packing
- comp: segmented (value,flag) associative min-scan (current) vs a
  block-min RMQ hierarchy (1 reduce pass + tiny tables + extraction)
"""
import sys, os, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax, jax.numpy as jnp, numpy as np
from lux_tpu.utils.platform import ensure_backend
print("platform:", ensure_backend(), file=sys.stderr)
from lux_tpu.engine.pull import hard_sync

ONLY = set(sys.argv[1:])


def timed(name, fn, *args, per=None):
    if ONLY and name.split()[0] not in ONLY:
        return
    f = jax.jit(fn)
    try:
        t0 = time.perf_counter()
        hard_sync(f(jnp.int32(3), *args))
        print(f"# {name}: compile+first {time.perf_counter()-t0:.1f}s",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"{name:46s} FAILED: {type(e).__name__}: {str(e)[:120]}",
              flush=True)
        return None
    ts = {}
    for n in (3, 13):
        best = 1e9
        for _ in range(2):
            t0 = time.perf_counter()
            hard_sync(f(jnp.int32(n), *args))
            best = min(best, time.perf_counter() - t0)
        ts[n] = best
    dt = (ts[13] - ts[3]) / 10
    unit = f"  ({dt/per*1e9:.3f} ns/item)" if per else ""
    print(f"{name:46s} {dt*1e3:8.2f} ms{unit}", flush=True)
    return dt


rng = np.random.default_rng(0)
NVB = 32768
C = 1 << 17
K = 60
M = C * K
iota = jnp.arange(128, dtype=jnp.int32)

xu = jnp.asarray(
    rng.integers(0, 1 << 31, (NVB, 128), dtype=np.int64).astype(np.uint32)
)
xf = jnp.asarray(rng.standard_normal((NVB, 128), dtype=np.float32))
sb = jnp.asarray(rng.integers(0, NVB, (K, C), dtype=np.int32))
lane = jnp.asarray(rng.integers(0, 128, (K, C), dtype=np.int8))
wch = jnp.asarray(rng.integers(1, 5, (K, C), dtype=np.int32))


def loop(n, body, x, *chunks):
    def outer(i, acc):
        def inner(c, a):
            return a + body(x + (a[0] * 0).astype(x.dtype),
                            tuple(t[c] for t in chunks))
        return jax.lax.fori_loop(0, K, inner, acc)
    return jax.lax.fori_loop(0, n, outer, jnp.zeros((C,), jnp.float32))


def v_u32_load(x, ch):
    s, l = ch
    rows = x[s]
    pk = jnp.where(
        l.astype(jnp.int32)[:, None] == iota[None, :], rows, 0
    ).sum(axis=1, dtype=jnp.uint32)
    sv = pk & jnp.uint32(0x7FFFFFFF)
    active = (pk >> 31).astype(bool)
    cand = sv + jnp.uint32(1)          # SSSP relax (hop count)
    out = jnp.where(active, cand, jnp.uint32(0xFFFFFFFF))
    return out.astype(jnp.float32)     # fold into f32 acc for the loop


def v_f32_load(x, ch):
    s, l = ch
    rows = x[s]
    pk = jnp.where(
        l.astype(jnp.int32)[:, None] == iota[None, :], rows, 0.0
    ).sum(axis=1)
    active = pk < 0
    sv = jnp.abs(pk) - 1.0
    cand = sv + 1.0
    return jnp.where(active, cand, jnp.float32(3.4e38))


print(f"blocked-dense LOAD variants over {M/1e6:.1f}M edges:", flush=True)
timed("u32 packed load (current)",
      lambda n, x, s, l: loop(n, v_u32_load, x, s, l), xu, sb, lane, per=M)
timed("f32 sign-packed load",
      lambda n, x, s, l: loop(n, v_f32_load, x, s, l), xf, sb, lane, per=M)

# ---- comp variants: per-segment min over sorted segments --------------
NE = M
NV = 1 << 22
# synthetic sorted segments: row_ptr via random degrees
deg = rng.multinomial(NE, np.ones(NV) / NV)
rp = np.zeros(NV + 1, np.int64)
np.cumsum(deg, out=rp[1:])
seg_start_np = np.zeros(NE, bool)
starts = rp[:-1]
seg_start_np[starts[starts < NE]] = True
data = jnp.asarray(
    rng.integers(0, 1 << 24, NE, dtype=np.int64).astype(np.uint32)
)
dataf = jnp.asarray(rng.standard_normal(NE, dtype=np.float32))
seg_start = jnp.asarray(seg_start_np)
end_pos = jnp.asarray(np.clip(rp[1:] - 1, 0, NE - 1).astype(np.int32))
nonempty = jnp.asarray(deg > 0)


def v_assoc(n, d, ss, ep, ne_):
    from lux_tpu.ops.segment import segment_minmax_by_rowptr

    def body(i, acc):
        dd = d + (acc[0] * 0).astype(d.dtype)
        return acc + segment_minmax_by_rowptr(
            dd, ss, ep, ne_, "min"
        ).astype(jnp.float32)
    return jax.lax.fori_loop(0, n, body, jnp.zeros(NV, jnp.float32))


timed(f"assoc-scan seg-min {NE/1e6:.0f}M (current)", v_assoc,
      data, seg_start, end_pos, nonempty, per=NE)

# RMQ block-min variant (f32): block mins + log2 sparse table + per-dst
# head/tail partial rows with segmented gather tables.
BL = 128
nb = NE // BL
levels = int(np.floor(np.log2(max(nb, 2))))
srow = jnp.asarray((starts // BL).astype(np.int32))
erow = jnp.asarray(((rp[1:] - 1).clip(0) // BL).astype(np.int32))
s_np, e_np = starts, rp[1:]
bl_np = -(-s_np // BL)
br_np = (e_np // BL)
has_int = (br_np > bl_np) & (deg > 0)
intlen = np.maximum(br_np - bl_np, 1)
klev = np.floor(np.log2(intlen)).astype(np.int32)
kpow = (1 << klev).astype(np.int64)
g1 = jnp.asarray(bl_np.astype(np.int32))
g2 = jnp.asarray((br_np - kpow).clip(0).astype(np.int32))
klev_j = jnp.asarray(klev)
has_int_j = jnp.asarray(has_int)
smask = jnp.asarray(
    (np.arange(BL)[None, :] >= (s_np % BL)[:, None])
)
# head row covers [s, min(ceil(s/BL)*BL, e)); tail row [max(br*BL, s), e)
emask = jnp.asarray(
    (np.arange(BL)[None, :] < ((e_np - 1) % BL + 1)[:, None])
)
head_valid_to = jnp.asarray(np.minimum(bl_np * BL, e_np))
tail_valid_from = jnp.asarray(np.maximum(br_np * BL, s_np))
sp = jnp.asarray(s_np.astype(np.int64))
ep64 = jnp.asarray(e_np.astype(np.int64))


def v_rmq(n, d):
    BIG = jnp.float32(3.4e38)

    def body(i, acc):
        dd = d + acc[0] * 0
        d2 = dd.reshape(nb, BL)
        m0 = d2.min(axis=1)                      # block mins, 1 pass
        tabs = [m0]
        t = m0
        for k in range(1, levels + 1):
            sh = 1 << (k - 1)
            cur = t.shape[0] - sh
            t = jnp.minimum(t[:cur], t[sh : sh + cur])
            tabs.append(t)
        # interior via sparse table: two gathers at level klev
        stacked = jnp.concatenate(
            [jnp.pad(t, (0, nb - t.shape[0]), constant_values=BIG)
             for t in tabs]
        ).reshape(levels + 1, nb)
        i1 = stacked[klev_j, g1]
        i2 = stacked[klev_j, g2]
        interior = jnp.where(has_int_j, jnp.minimum(i1, i2), BIG)
        # head/tail partial rows
        iot = jnp.arange(BL, dtype=jnp.int32)
        hr = d2[srow]
        pos_h = srow.astype(jnp.int64)[:, None] * BL + iot[None, :]
        mh = (pos_h >= sp[:, None]) & (pos_h < head_valid_to[:, None])
        head = jnp.where(mh, hr, BIG).min(axis=1)
        tr = d2[erow]
        pos_t = erow.astype(jnp.int64)[:, None] * BL + iot[None, :]
        mt = (pos_t >= tail_valid_from[:, None]) & (pos_t < ep64[:, None])
        tail = jnp.where(mt, tr, BIG).min(axis=1)
        res = jnp.minimum(jnp.minimum(head, tail), interior)
        return acc + res
    return jax.lax.fori_loop(0, n, body, jnp.zeros(NV, jnp.float32))


timed(f"rmq seg-min {NE/1e6:.0f}M (f32)", v_rmq, dataf, per=NE)
