#!/usr/bin/env python3
"""Profiler smoke test (`make prof-smoke`).

End-to-end acceptance run for the device-timeline profiler (obs/prof.py)
on a 2x4 virtual CPU mesh (8 XLA host devices — the exchange-smoke
trick, so this runs in CI with no TPU). A REAL capture, not a synthetic
trace: jax.profiler writes the artifact, the stdlib parser reads it
back.

1. build + warm a sharded pull engine under a RecompileSentinel expect
   window (the AOT op-map lowering's one compile is budgeted there),
   then run a profiled capture window over warm steps under a WATCH
   window — zero added recompiles with regions armed;
2. prove classification: both ``lux.pull_sharded.exchange`` and
   ``.compute`` tags present in the parsed report, plus the host-side
   wrapper region;
3. prove the interval math on every device row: union >= max phase,
   union <= exchange+compute, overlap <= min phase,
   realized_hidden_frac and idle_frac in [0, 1];
4. prove the artifact contract: the written ``profile_v1.json``
   round-trips through ``tools/prof_summary.py --validate``;
5. serve integration: ``POST /profilez`` is 403 while LUX_PROF_DIR is
   unset, 429 while another capture holds the window, and 200 with a
   validating profile.v1 report under a concurrent query burst — zero
   failed queries while the capture runs;
6. the /statusz engobs block labels ``exchange_hidden_frac`` as the
   budget (upper bound) and carries the device-measured
   ``realized_hidden_frac`` next to it once a profile exists.

Prints a ``prof_smoke.v1`` JSON document on the last line.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MESH = "2x4"
PARTS = 8
STEPS = 4
EPS = 1e-3      # float-microsecond tolerance (obs/prof.py _EPS_US)


def log(msg):
    print(f"# {msg}", flush=True)


def post(base, path, payload, timeout=600):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def check_device_math(rep):
    """Invariant sweep over every device row (smoke re-derives them —
    the parser's validate() already ran, this proves it from outside)."""
    for pid, d in rep["devices"].items():
        ex, co = d["exchange_us"], d["compute_us"]
        ov, un = d["overlap_us"], d["union_us"]
        assert un + EPS >= max(ex, co), (pid, d)
        assert un <= ex + co + EPS, (pid, d)
        assert ov <= min(ex, co) + EPS, (pid, d)
        for key in ("realized_hidden_frac", "idle_frac"):
            v = d.get(key)
            assert v is None or 0.0 <= v <= 1.0, (pid, key, v)
    frac = rep["realized_hidden_frac"]
    assert frac is None or 0.0 <= frac <= 1.0, frac


def main() -> int:
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(PARTS)
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.analysis.sentinel import RecompileSentinel
    from lux_tpu.engine.pull_sharded import ShardedPullExecutor, hard_sync
    from lux_tpu.graph import generate
    from lux_tpu.models import PageRank
    from lux_tpu.obs import prof
    from lux_tpu.parallel.mesh import make_mesh

    work = tempfile.mkdtemp(prefix="prof_smoke_")
    doc = {"schema": "prof_smoke.v1",
           "mesh": {"spec": MESH, "num_parts": PARTS}}
    sent = RecompileSentinel("prof-smoke")

    # -- 1: capture over warm steps, zero recompiles with regions armed -
    g = generate.halo(PARTS, 256, hubs=8, weighted=False)
    mesh = make_mesh(PARTS)
    log(f"halo graph nv={g.nv} ne={g.ne} on a {MESH} virtual mesh")
    with sent.expect("pagerank-sharded"):
        ex = ShardedPullExecutor(g, PageRank(), mesh=mesh)
        ex.warmup()
        vals = hard_sync(ex.step(ex.init_values()))
        # AOT lowering for the HLO op-name map: exactly one budgeted
        # compile (obs/prof.py op_map_for).
        opmap = prof.op_map_for(ex._step, vals, ex._device_graph)
    assert set(opmap["ops"].values()) >= {
        "lux.pull_sharded.exchange", "lux.pull_sharded.compute"}, (
        "compiled HLO carries no region metadata: "
        f"{sorted(set(opmap['ops'].values()))}")

    def drive():
        with prof.region("lux.prof_smoke.drive"):
            v = vals
            for _ in range(STEPS):
                v = ex.step(v)
            return hard_sync(v)

    cap_dir = os.path.join(work, "capture")
    with sent.watch("pagerank-sharded"):
        # step() donates its input, so each step consumes `vals` and the
        # warm run must rebind it (drive reads the rebound cell).
        vals = hard_sync(ex.step(vals))       # warm, unprofiled
        _, rep = prof.profile_window(
            drive, dirname=cap_dir, steps=STEPS, op_maps=[opmap])
    sent.assert_zero_recompiles()
    log("sentinel: 0 recompiles outside expect windows — regions armed "
        "and capture running add no re-traces")

    # -- 2: both phase tags classified + host wrapper region ------------
    tags = set(rep["tags"])
    assert {"lux.pull_sharded.exchange",
            "lux.pull_sharded.compute"} <= tags, tags
    assert "lux.prof_smoke.drive" in rep["host_regions"], (
        rep["host_regions"])
    log(f"classification: tags={sorted(tags)}")

    # -- 3: interval math + steps cross-check ---------------------------
    check_device_math(rep)
    assert rep["steps"]["captured"] == STEPS, rep["steps"]
    assert prof.latest() is rep and \
        prof.latest_realized() == rep["realized_hidden_frac"]
    realized = rep["realized_hidden_frac"]
    log(f"interval math consistent on {len(rep['devices'])} device "
        f"row(s); realized_hidden_frac={realized}")
    doc["engine_capture"] = {
        "devices": len(rep["devices"]),
        "realized_hidden_frac": realized,
        "tags": sorted(tags),
    }

    # -- 4: profile_v1.json round-trips the CLI validator ---------------
    rep_path = os.path.join(work, "profile_v1.json")
    with open(rep_path, "w") as f:
        json.dump(rep, f)
    for target in (rep_path, cap_dir):
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "prof_summary.py"),
             "--validate", target], cwd=REPO).returncode
        assert rc == 0, f"prof_summary --validate {target} -> rc={rc}"
    render = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "prof_summary.py"),
         rep_path], cwd=REPO, capture_output=True, text=True)
    assert render.returncode == 0 and \
        "realized_hidden_frac" in render.stdout, render.stdout
    log("prof_summary: --validate ok on the report AND the raw capture "
        "dir; render carries the realized fraction")

    # -- 5: serve integration -------------------------------------------
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    os.environ["LUX_ENGOBS"] = "1"
    try:
        gs = generate.rmat(8, 8, seed=3)
        session = Session(gs, ServeConfig(
            max_batch=4, window_s=0.02, max_queue=256,
            pagerank_iters=4, mesh=MESH))
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"

        # 5a: unarmed -> 403 (flags registry default is unset)
        os.environ.pop("LUX_PROF_DIR", None)
        try:
            status, _ = post(base, "/profilez", {"steps": 2})
        except urllib.error.HTTPError as e:
            status = e.code
        assert status == 403, f"unarmed /profilez returned {status}"

        # 5b: busy window -> 429 (deterministic: hold the capture lock)
        os.environ["LUX_PROF_DIR"] = os.path.join(work, "serve_prof")
        assert prof._capture_lock.acquire(blocking=False)
        try:
            try:
                status, _ = post(base, "/profilez", {"steps": 2})
            except urllib.error.HTTPError as e:
                status = e.code
            assert status == 429, f"busy /profilez returned {status}"
        finally:
            prof._capture_lock.release()

        # 5c: capture under a concurrent query burst — 0 failed queries
        errors = []

        def one(i):
            try:
                app = "pagerank" if i % 2 else "sssp"
                payload = {"app": app}
                if app == "sssp":
                    payload["start"] = i % gs.nv
                status, out = post(base, "/query", payload)
                assert status == 200, (status, out)
                return out
            except Exception as e:   # any failure fails the smoke
                errors.append((i, repr(e)))
                return None

        one(0)                        # warm the engines pre-burst
        one(1)
        with ThreadPoolExecutor(max_workers=6) as tp:
            futs = [tp.submit(one, i) for i in range(8)]
            prof_fut = tp.submit(post, base, "/profilez",
                                 {"steps": STEPS})
            futs += [tp.submit(one, i) for i in range(8, 12)]
            status, serve_rep = prof_fut.result()
            burst = [f.result() for f in futs]
        assert not errors, f"queries failed during capture: {errors}"
        assert status == 200, (status, serve_rep)
        serve_rep = prof.validate(serve_rep)
        check_device_math(serve_rep)
        log(f"/profilez: 200 with a validating profile.v1 under "
            f"{len(burst)} concurrent queries, 0 failed; "
            f"realized={serve_rep['realized_hidden_frac']}")
        doc["serve_capture"] = {
            "queries": len(burst), "failed": 0,
            "realized_hidden_frac": serve_rep["realized_hidden_frac"],
            "statuses": {"unarmed": 403, "busy": 429, "armed": 200},
        }

        # -- 6: /statusz budget labeling next to the realized number ----
        statusz = get(base, "/statusz")
        engblock = statusz["mesh"]["engobs"]
        labeled = {k: r for k, r in engblock.items()
                   if "exchange_hidden_frac_note" in r}
        assert labeled, (
            "LUX_ENGOBS=1 serve run produced no budget-labeled engobs "
            f"records: {engblock}")
        for kind, r in labeled.items():
            assert r["exchange_hidden_frac_note"] == \
                "budget (upper bound)", (kind, r)
            assert 0.0 <= r["realized_hidden_frac"] <= 1.0, (kind, r)
        log(f"/statusz: {len(labeled)} engobs record(s) label the "
            "budget and carry realized_hidden_frac beside it")
        doc["statusz_budget_labeled"] = len(labeled)

        server.shutdown()
        session.close()
    finally:
        del os.environ["LUX_ENGOBS"]
        os.environ.pop("LUX_PROF_DIR", None)

    shutil.rmtree(work, ignore_errors=True)
    print("prof-smoke PASS (real capture parsed, both phases tagged, "
          "zero recompiles with regions armed, /profilez guarded + "
          "concurrent-safe, budget labeled)")
    print("PROF_SMOKE " + json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
