#!/usr/bin/env python3
"""Render / validate ``profile.v1`` device-timeline reports.

Input (positional PATH), any of:

- a ``profile_v1.json`` report written by a capture window
  (``bench.py --profile``, ``POST /profilez``, SIGUSR2 toggle);
- a raw ``*.trace.json.gz`` Chrome-trace artifact (jax.profiler);
- a capture directory — the newest trace artifact under it is parsed.

Default output is the human table (obs/prof.py ``format_report``:
per-device exchange/compute/overlap interval unions, the
device-measured ``realized_hidden_frac``, idle fraction, top ops,
steps-per-second cross-check). ``--json`` prints the validated report
JSON instead; ``--validate`` prints nothing and exits 0/1 — the smoke
and CI hooks use it as a schema gate. Parsing is stdlib-only (json +
gzip); a truncated or malformed artifact fails loudly with
``ProfileParseError``, never a half-filled report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lux_tpu.obs import prof  # noqa: E402


def load_report(path: str, top_k: int) -> dict:
    """PATH -> validated profile.v1 report (see module docstring for
    the accepted shapes)."""
    if os.path.isdir(path):
        return prof.parse_dir(path, top_k=top_k)
    if path.endswith(".json") and not path.endswith(".trace.json"):
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema") == "profile.v1":
            return prof.validate(doc)
        # A bare (uncompressed) Chrome trace dump also arrives as .json.
        return prof.parse_events(doc, top_k=top_k)
    return prof.parse(path, top_k=top_k)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="profile_v1.json | *.trace.json.gz | "
                    "capture directory")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="print the validated report JSON")
    ap.add_argument("--validate", action="store_true",
                    help="validate only: no output, exit 0/1")
    ap.add_argument("--top-k", type=int, default=10,
                    help="op-table rows when parsing a raw trace")
    args = ap.parse_args(argv)

    try:
        rep = load_report(args.path, args.top_k)
    except (prof.ProfileParseError, OSError, json.JSONDecodeError) as e:
        print(f"INVALID {args.path}: {e}", file=sys.stderr)
        return 1
    if args.validate:
        print(f"valid profile.v1: {args.path}", file=sys.stderr)
        return 0
    if args.as_json:
        print(json.dumps(rep, indent=1, sort_keys=True))
    else:
        print(prof.format_report(rep))
    return 0


if __name__ == "__main__":
    sys.exit(main())
