#!/usr/bin/env python3
"""Concurrency stress harness with LockWatch armed (`make race-stress`).

Runtime witness for the luxlint-threads tier: the static rules
(LUX301-305) prove lock discipline on the AST; this tool proves it on
actual interleavings. With ``LUX_LOCKWATCH=1`` set *before* import —
module-level obs locks are wrapped at construction — it drives:

1. a concurrent query burst (SSSP / components / PageRank) through the
   MicroBatcher from a thread pool;
2. a mid-burst snapshot hot-swap (``apply_edits``: background warm,
   atomic flip, FIFO drain barrier);
3. a forced background compaction (LUX_DELTA_COMPACT_RATIO pinned low)
   drained afterwards;

and asserts the run stays disciplined:

- ZERO lock-order inversions in the observed acquisition graph,
- ZERO failed queries across the swap,
- the pool's zero-recompile sentinel stays green,
- every watched lock's hold-time p99 stays bounded (the pool lock gets
  a compile-sized budget — first-build warmup holds it by design; every
  other lock must be orders of magnitude cheaper).

Prints a one-line ``race_stress.v1`` JSON document last. Scale with
LUX_SMOKE_SCALE (default 10); CPU-sized.
"""

from __future__ import annotations

import json
import os
import sys
from concurrent.futures import ThreadPoolExecutor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Before any lux_tpu import: locks are wrapped at construction, and the
# obs modules build theirs at import time.
os.environ["LUX_LOCKWATCH"] = "1"
# Every swap's delta crosses the threshold -> compaction is forced.
os.environ.setdefault("LUX_DELTA_COMPACT_RATIO", "0.000001")
os.environ.setdefault("LUX_PLATFORM", "cpu")

import numpy as np  # noqa: E402

# Locks the serve/graph/obs layers register via make_lock; the pool lock
# is allowed a compile-sized hold (build-under-lock is the documented
# single-compile guarantee), everything else must stay snappy.
POOL_HOLD_P99_S = 300.0
HOLD_P99_S = 30.0
WATCHED = ("pool", "cache", "session.swap", "snapshot", "snapshot.store",
           "delta.merge", "obs.spans", "obs.trace", "obs.flight", "obs.slo")


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")

    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.graph import EdgeEdits, generate
    from lux_tpu.obs import metrics
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.utils.locks import WATCH, hold_quantile

    g = generate.rmat(scale, 8, seed=7)
    cfg = ServeConfig(max_batch=4, window_s=0.02, max_queue=512,
                      pagerank_iters=3)
    session = Session(g, cfg)

    rng = np.random.default_rng(23)
    roots = [int(r) for r in rng.integers(0, g.nv, size=8)]
    n_edit = max(4, g.ne // 200)
    ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
           for _ in range(n_edit // 2)]
    dels = [(int(g.col_src[e]), int(g.col_dst[e]))
            for e in rng.choice(g.ne, size=n_edit - n_edit // 2,
                                replace=False)]
    edits = EdgeEdits.from_lists(insert=ins, delete=dels)

    jobs = ([("sssp", {"start": r}) for r in roots] * 4
            + [("components", {})] * 4 + [("pagerank", {})] * 4)
    errors = []

    def one(job):
        app, params = job
        try:
            session.query(app, timeout=300, **params)
            return 1
        except Exception as e:   # any failure fails the stress run
            errors.append((app, params, repr(e)))
            return 0

    # Mid-burst swap: first half of the burst in flight, then the swap
    # races the second half through the FIFO drain barrier.
    with ThreadPoolExecutor(max_workers=8) as tp:
        futs = [tp.submit(one, j) for j in jobs[: len(jobs) // 2]]
        swap_fut = tp.submit(session.apply_edits, edits)
        futs += [tp.submit(one, j) for j in jobs[len(jobs) // 2:]]
        served = sum(f.result() for f in futs)
        summary = swap_fut.result()

    session.store.drain_compactions()
    compactions = metrics.counter("lux_snapshot_compactions_total").value
    assert not errors, f"{len(errors)} queries failed: {errors[:3]}"
    assert summary["version"] == 1, summary
    assert compactions >= 1, "forced compaction never ran"

    # -- the discipline asserts -----------------------------------------
    WATCH.assert_no_inversions()
    session.pool.sentinel.assert_zero_recompiles()
    hold_p99 = {}
    for name in WATCHED:
        q = hold_quantile(name, 0.99)
        if q is None:
            continue   # lock exists but saw no traffic at this scale
        hold_p99[name] = round(q, 6)
        budget = POOL_HOLD_P99_S if name == "pool" else HOLD_P99_S
        assert q < budget, (
            f"lock {name} hold p99 {q:.3f}s exceeds {budget:.0f}s budget")
    stats = WATCH.stats()
    session.close()

    print(f"race-stress PASS ({served} queries, 1 swap, "
          f"{int(compactions)} compaction(s), {stats['edges']} lock-order "
          f"edges, 0 inversions, 0 recompiles)")
    print(json.dumps({
        "schema": "race_stress.v1",
        "graph": {"scale": scale, "nv": g.nv, "ne": g.ne},
        "queries": served,
        "failed": 0,
        "swaps": 1,
        "swap_s": round(summary["swap_s"], 3),
        "compactions": int(compactions),
        "inversions": 0,
        "lock_order_edges": stats["edges"],
        "hold_p99_s": dict(sorted(hold_p99.items())),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
