#!/usr/bin/env python3
"""Run PageRank end-to-end at the reference's headline scale (RMAT27,
2^31 edges — /root/reference/README.md:84) on a virtual CPU mesh, with a
sampled float64 parity check each iteration.

The graph (10.2 GB .lux) is memory-mapped (read_lux_mmap), sharded via
the memory-lean ShardedGraph.build (per-part slices only; no global
col_dst expansion), executed by the flat ShardedPullExecutor over P
virtual CPU devices, and verified per iteration on a vertex sample: for
each sampled destination, the expected new value is recomputed in
float64 from the previous iteration's full value vector and the mmap'd
in-edge slice. Wall times on this 2-core host measure correctness and
capability, not speed (P virtual devices share 2 cores — see
SHARDED_r02.json for the collective-volume scaling model).

Usage: python tools/run_rmat27.py [--file F] [--parts 8] [--ni 3]
       [--sample 4096] [--out RMAT27_r03.json]
"""
import argparse
import json
import os
import resource
import sys
import time

os.environ.setdefault("LUX_PLATFORM", "cpu")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--file", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".bench_cache", "rmat27_16.lux"))
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--ni", type=int, default=3)
    ap.add_argument("--sample", type=int, default=4096)
    ap.add_argument("--sum", default="rowptr", choices=["rowptr", "segment"])
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "RMAT27_r03.json"))
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.parts}"
    ).strip()
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )

    def log(msg):
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        print(f"# [{time.strftime('%H:%M:%S')} rss={rss:.1f}G] {msg}",
              file=sys.stderr, flush=True)

    from lux_tpu.utils.platform import ensure_backend

    log(f"platform: {ensure_backend()}")

    import numpy as np

    from lux_tpu.engine.pull import hard_sync
    from lux_tpu.engine.pull_sharded import ShardedPullExecutor
    from lux_tpu.graph import read_lux_mmap
    from lux_tpu.models.pagerank import ALPHA, PageRank
    from lux_tpu.parallel.mesh import make_mesh
    from lux_tpu.parallel.shard import ShardedGraph

    t0 = time.time()
    g = read_lux_mmap(args.file)
    log(f"mapped {args.file}: nv={g.nv} ne={g.ne} in {time.time()-t0:.0f}s")

    t0 = time.time()
    sg = ShardedGraph.build(g, args.parts)
    log(f"sharded build P={args.parts} max_nv={sg.max_nv} "
        f"max_ne={sg.max_ne} in {time.time()-t0:.0f}s")

    t0 = time.time()
    ex = ShardedPullExecutor(g, PageRank(), mesh=make_mesh(args.parts),
                             sg=sg, sum_strategy=args.sum)
    sg.release_edge_arrays()   # device copies exist now; drop host ~13 B/edge
    log(f"executor built in {time.time()-t0:.0f}s")

    # Sample: random dsts + the highest in-degree hubs + guaranteed sinks
    rng = np.random.default_rng(27)
    in_deg = np.diff(g.row_ptr)
    hubs = np.argsort(in_deg)[-16:]
    sample = np.unique(np.concatenate([
        rng.integers(0, g.nv, args.sample), hubs,
    ])).astype(np.int64)
    deg64 = g.out_degrees.astype(np.float64)
    # Degree-aware parity criterion: an f32 engine (ours, or the
    # reference's f32 atomicAdd accumulation) sums a hub's in-edge mass
    # with absolute error ~ eps32 * mass while the stored pre-divided
    # value shrinks with out-degree, so RELATIVE error on high-in-degree
    # vertices grows mechanically with no bug present. Low-degree
    # vertices must meet a tight relative bound; hubs a tight absolute
    # one (their error is eps-scale mass noise, ~1e-13 observed).
    HUB_DEG = 4096
    low = in_deg[sample] <= HUB_DEG

    def expected_sampled(prev_full):
        """float64 oracle for the sampled dsts from the previous values."""
        prev64 = prev_full.astype(np.float64)
        exp = np.empty(sample.shape[0], dtype=np.float64)
        for i, v in enumerate(sample):
            s, e = int(g.row_ptr[v]), int(g.row_ptr[v + 1])
            srcs = np.asarray(g.col_src[s:e]).astype(np.int64)
            r = (1.0 - ALPHA) / g.nv + ALPHA * prev64[srcs].sum()
            exp[i] = r if deg64[v] == 0 else r / deg64[v]
        return exp

    t0 = time.time()
    vals = ex.init_values()
    prev_full = ex.gather_values(vals)
    log(f"init + gather in {time.time()-t0:.0f}s")

    t0 = time.time()
    vals = ex.step(vals)
    hard_sync(vals)
    log(f"first step (compile + run) in {time.time()-t0:.0f}s")
    # That step consumed iteration 1: verify it, then continue timing.
    iter_times = [time.time() - t0]
    parity = []

    def check(it, new_full, prev_full):
        exp = expected_sampled(prev_full)
        got = new_full[sample].astype(np.float64)
        abs_err = np.abs(got - exp)
        rel = abs_err / np.maximum(np.abs(exp), 1e-300)
        rec = {"iter": it,
               "low_deg_max_rel": float(rel[low].max()),
               "hub_max_abs": float(abs_err[~low].max()) if (~low).any()
               else 0.0,
               "max_abs": float(abs_err.max())}
        parity.append(rec)
        log(f"iter {it} parity: low-deg max_rel={rec['low_deg_max_rel']:.3e} "
            f"hub max_abs={rec['hub_max_abs']:.3e}")

    new_full = ex.gather_values(vals)
    check(1, new_full, prev_full)
    prev_full = new_full

    for it in range(2, args.ni + 1):
        t0 = time.time()
        vals = ex.step(vals)
        hard_sync(vals)
        dt = time.time() - t0
        iter_times.append(dt)
        new_full = ex.gather_values(vals)
        check(it, new_full, prev_full)
        prev_full = new_full

    ok = all(
        p["low_deg_max_rel"] < 1e-3 and p["hub_max_abs"] < 1e-8
        for p in parity
    )
    out = {
        "metric": "pagerank_rmat27_end_to_end_cpu_mesh",
        "file": args.file,
        "nv": g.nv,
        "ne": g.ne,
        "parts": args.parts,
        "iters": args.ni,
        "sec_per_iter": [round(t, 1) for t in iter_times],
        "steady_sec_per_iter": round(
            float(np.mean(iter_times[1:])) if len(iter_times) > 1
            else iter_times[0], 1),
        "sampled_vertices": int(sample.shape[0]),
        "hub_degree_threshold": HUB_DEG,
        "parity": parity,
        "parity_ok": ok,
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 1),
        "note": ("P virtual CPU devices share 2 host cores — wall time "
                 "demonstrates end-to-end capability at 2^31 edges, not "
                 "throughput; collective-volume scaling model in "
                 "SHARDED_r02.json / PERF.md"),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    log(f"wrote {args.out} parity_ok={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
