#!/usr/bin/env python3
"""Consume the RMAT27 hybrid plan: run the sharded TILED executor at
the reference's headline scale (2^31 edges) on the virtual CPU mesh.

RMAT27_r03.json proved the flat sharded engine end-to-end; this run
proves the banded-planner output (PLAN27, 8.39M strips) actually FEEDS
an executor: ShardedTiledExecutor over P virtual devices with the
cached plan, ≥2 PageRank iterations, per-iteration wall time, the
analytic per-device collective bytes, and a sampled float64 parity
check (same degree-aware criterion as tools/run_rmat27.py). Wall
times measure 2 shared host cores, not scaling.

Usage: python tools/run_rmat27_tiled.py [--parts 8] [--ni 2]
"""
import argparse
import json
import os
import resource
import sys
import time

os.environ.setdefault("LUX_PLATFORM", "cpu")


def main():
    ap = argparse.ArgumentParser()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap.add_argument("--file", default=os.path.join(
        repo, ".bench_cache", "rmat27_16.lux"))
    ap.add_argument("--plan", default=os.path.join(
        repo, ".bench_cache", "plan_rmat27_16_8x2_8192.luxplan"))
    ap.add_argument("--parts", type=int, default=8)
    ap.add_argument("--ni", type=int, default=2)
    ap.add_argument("--sample", type=int, default=2048)
    ap.add_argument("--out", default=os.path.join(
        repo, "RMAT27_TILED_r03.json"))
    args = ap.parse_args()

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={args.parts}"
    ).strip()
    sys.path.insert(0, repo)

    def log(msg):
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
        print(f"# [{time.strftime('%H:%M:%S')} rss={rss:.1f}G] {msg}",
              file=sys.stderr, flush=True)

    from lux_tpu.utils.platform import ensure_backend

    log(f"platform: {ensure_backend()}")

    import numpy as np

    from lux_tpu.engine.pull import hard_sync
    from lux_tpu.engine.tiled_sharded import ShardedTiledExecutor
    from lux_tpu.graph import read_lux_mmap
    from lux_tpu.models.pagerank import ALPHA, PageRank
    from lux_tpu.ops.tiled_spmv import load_plan
    from lux_tpu.parallel.mesh import make_mesh

    t0 = time.time()
    g = read_lux_mmap(args.file)
    log(f"mapped {args.file}: nv={g.nv} ne={g.ne} in {time.time()-t0:.0f}s")
    t0 = time.time()
    plan = load_plan(args.plan)
    log(f"plan loaded: {plan.num_strips} strips "
        f"({plan.strip_bytes/1e9:.2f} GB), coverage={plan.coverage:.1%} "
        f"in {time.time()-t0:.0f}s")

    t0 = time.time()
    ex = ShardedTiledExecutor(
        g, PageRank(), mesh=make_mesh(args.parts), plan=plan,
    )
    log(f"executor built in {time.time()-t0:.0f}s (max_nv={ex.max_nv})")

    rng = np.random.default_rng(27)
    in_deg = np.diff(g.row_ptr)
    hubs = np.argsort(in_deg)[-8:]
    sample = np.unique(np.concatenate([
        rng.integers(0, g.nv, args.sample), hubs,
    ])).astype(np.int64)
    deg64 = g.out_degrees.astype(np.float64)
    HUB_DEG = 4096
    low = in_deg[sample] <= HUB_DEG

    def expected_sampled(prev_full):
        prev64 = prev_full.astype(np.float64)
        exp = np.empty(sample.shape[0], dtype=np.float64)
        for i, v in enumerate(sample):
            s, e = int(g.row_ptr[v]), int(g.row_ptr[v + 1])
            srcs = np.asarray(g.col_src[s:e]).astype(np.int64)
            r = (1.0 - ALPHA) / g.nv + ALPHA * prev64[srcs].sum()
            exp[i] = r if deg64[v] == 0 else r / deg64[v]
        return exp

    vals = ex.init_values()
    prev_full = ex.gather_values(vals)
    log("init + gather done")

    # First step isolated: it folds shard_map/jit compile time in
    # (reported separately, like tools/run_rmat27.py's steady mean).
    t0 = time.time()
    vals = ex.step(vals)
    hard_sync(vals)
    compile_step = time.time() - t0
    log(f"first step (compile + run) in {compile_step:.0f}s")
    new_full = ex.gather_values(vals)
    exp = expected_sampled(prev_full)
    got = new_full[sample].astype(np.float64)
    abs_err = np.abs(got - exp)
    rel = abs_err / np.maximum(np.abs(exp), 1e-300)
    parity = [{"iter": 1,
               "low_deg_max_rel": float(rel[low].max()),
               "hub_max_abs": float(abs_err[~low].max())
               if (~low).any() else 0.0}]
    log(f"iter 1 parity low-rel={parity[0]['low_deg_max_rel']:.3e} "
        f"hub-abs={parity[0]['hub_max_abs']:.3e}")
    prev_full = new_full

    iter_times = []
    for it in range(2, args.ni + 1):
        t0 = time.time()
        vals = ex.step(vals)
        hard_sync(vals)
        dt = time.time() - t0
        iter_times.append(dt)
        new_full = ex.gather_values(vals)
        exp = expected_sampled(prev_full)
        got = new_full[sample].astype(np.float64)
        abs_err = np.abs(got - exp)
        rel = abs_err / np.maximum(np.abs(exp), 1e-300)
        rec = {"iter": it,
               "low_deg_max_rel": float(rel[low].max()),
               "hub_max_abs": float(abs_err[~low].max())
               if (~low).any() else 0.0}
        parity.append(rec)
        log(f"iter {it}: {dt:.0f}s parity low-rel="
            f"{rec['low_deg_max_rel']:.3e} hub-abs={rec['hub_max_abs']:.3e}")
        prev_full = new_full

    ok = all(
        p["low_deg_max_rel"] < 1e-3 and p["hub_max_abs"] < 1e-8
        for p in parity
    )
    P = args.parts
    ag = (P - 1) * ex.max_nv * 4
    out = {
        "metric": "pagerank_rmat27_tiled_sharded_cpu_mesh",
        "nv": g.nv, "ne": g.ne, "parts": P, "iters": args.ni,
        "plan_strips": plan.num_strips,
        "plan_strip_gb": round(plan.strip_bytes / 1e9, 2),
        "plan_coverage": round(plan.coverage, 3),
        "first_step_incl_compile_sec": round(compile_step, 1),
        "steady_sec_per_iter": [round(x, 1) for x in iter_times],
        "all_gather_bytes_per_dev": ag,
        "reduce_scatter_bytes_per_dev": ag,
        "sampled_vertices": int(sample.shape[0]),
        "parity": parity,
        "parity_ok": ok,
        "peak_rss_gb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 1),
        "note": ("the round-2 RMAT27 hybrid plan (banded streaming "
                 "planner) consumed by the sharded tiled executor; P "
                 "virtual CPU devices share 2 host cores — wall time is "
                 "capability evidence, not throughput"),
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    log(f"wrote {args.out} parity_ok={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
