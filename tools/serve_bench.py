#!/usr/bin/env python3
"""Closed-loop load generator for the serving layer.

Spawns N worker threads, each issuing queries back-to-back (closed loop)
or paced to a per-worker QPS budget, against an in-process Session (the
default: measures engine+batcher latency without socket noise) or a
remote server via --url (measures the full HTTP path). Prints
p50/p95/p99 latency per app, throughput, and the achieved batch-size
histogram from the `obs` registry, and (with --json / --json-out) emits
a schema-versioned ``serve_bench.v1`` report — the evidence format
PERF.md specifies for serving claims, checkable against a baseline via
tools/slo_check.py (`make serve-slo`).

With ``--swap-at T`` (in-process mode) a ~1% random edit batch is
applied mid-run via ``session.apply_edits`` — the report gains a
``snapshot`` block {version, swap_s, errors_during_swap} so SLO checks
can assert hot-swaps are latency- and error-neutral under load.

With ``--mesh PxQ`` (in-process mode) the session serves from sharded
engines on a P*Q-device mesh (virtual XLA host devices on CPU) and the
report gains a ``mesh`` block {spec, num_parts, plans,
exchange_bytes_per_iter} — the serving half of the PERF.md multi-chip
evidence.

Examples:
  python tools/serve_bench.py --scale 12 --workers 16 --duration 10
  python tools/serve_bench.py --url http://127.0.0.1:8399 --workers 32
  python tools/serve_bench.py --swap-at 5 --duration 10 --json
  python tools/serve_bench.py --mesh 2x4 --swap-at 5 --json
  python tools/serve_bench.py --json-out /tmp/bench.json && \
      python tools/slo_check.py --input /tmp/bench.json --baseline slo.json
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def percentile(sorted_xs, q):
    if not sorted_xs:
        return 0.0
    i = min(int(q * len(sorted_xs)), len(sorted_xs) - 1)
    return sorted_xs[i]


class HttpClient:
    def __init__(self, url):
        self.url = url.rstrip("/")

    def query(self, payload, tenant=None):
        import urllib.request

        headers = {"Content-Type": "application/json"}
        if tenant:
            headers["X-Lux-Tenant"] = tenant
        req = urllib.request.Request(
            self.url + "/query", json.dumps(payload).encode(), headers,
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            return json.loads(r.read())

    def costz(self):
        import urllib.request

        with urllib.request.urlopen(self.url + "/costz", timeout=10) as r:
            return json.loads(r.read())

    def batch_histogram(self):
        import urllib.request

        with urllib.request.urlopen(
            self.url + "/metrics.json", timeout=10
        ) as r:
            snap = json.loads(r.read())["metrics"]
        for m in snap:
            if m["name"] == "lux_serve_batch_size":
                return m
        return None

    def stats(self):
        import urllib.request

        with urllib.request.urlopen(self.url + "/stats", timeout=10) as r:
            return json.loads(r.read())


class LocalClient:
    def __init__(self, session):
        self.session = session

    def query(self, payload, tenant=None):
        payload = dict(payload)
        app = payload.pop("app")
        payload.pop("full", None)
        return self.session.query(app, tenant=tenant, **payload)

    def costz(self):
        return self.session.costz()

    def batch_histogram(self):
        from lux_tpu.obs import metrics

        for m in metrics.snapshot():
            if m["name"] == "lux_serve_batch_size":
                return m
        return None

    def stats(self):
        return self.session.stats()


def worker(client, mix, nv, stop_at, qps, lat, errs, seed,
           tenant=None, tlat=None):
    rng = random.Random(seed)
    interval = 1.0 / qps if qps else 0.0
    while time.monotonic() < stop_at:
        t_next = time.monotonic() + interval
        app = rng.choices([m[0] for m in mix], [m[1] for m in mix])[0]
        payload = {"app": app}
        if app == "sssp":
            payload["start"] = rng.randrange(nv)
        t0 = time.perf_counter()
        try:
            client.query(payload, tenant=tenant)
            dt = time.perf_counter() - t0
            lat.setdefault(app, []).append(dt)
            if tenant is not None and tlat is not None:
                tlat.setdefault(tenant, []).append(dt)
        except Exception as e:
            errs[type(e).__name__] = errs.get(type(e).__name__, 0) + 1
        if interval:
            time.sleep(max(0.0, t_next - time.monotonic()))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", help="benchmark a remote server instead of "
                   "an in-process session")
    p.add_argument("--file", help="serve this .lux graph (in-process mode)")
    p.add_argument("--scale", type=int, default=12,
                   help="generate an R-MAT graph of this scale "
                   "(in-process mode without --file)")
    p.add_argument("--workers", type=int, default=16,
                   help="concurrent closed-loop clients")
    p.add_argument("--qps", type=float, default=0.0,
                   help="per-worker request rate (0 = unpaced closed loop)")
    p.add_argument("--duration", type=float, default=10.0, help="seconds")
    p.add_argument("--max-batch", type=int, default=8, dest="max_batch")
    p.add_argument("--window-ms", type=float, default=3.0, dest="window_ms")
    p.add_argument("--mesh", default=None,
                   help="serving mesh spec for the in-process session "
                   "('8' or 'PxQ'); on CPU the mesh is virtual (XLA "
                   "host devices). Default: LUX_SERVE_MESH")
    p.add_argument("--tenants", default=None,
                   help="comma-separated tenant labels round-robined "
                   "over workers (X-Lux-Tenant per request); the report "
                   "gains per-tenant latency quantiles + /costz cost "
                   "aggregates")
    p.add_argument("--sssp-weight", type=float, default=0.8,
                   dest="sssp_weight",
                   help="fraction of traffic that is SSSP root queries "
                   "(rest splits between pagerank and components)")
    p.add_argument("--swap-at", type=float, default=None, dest="swap_at",
                   help="seconds into the run to apply a ~1%% random "
                   "edit batch and hot-swap serving (in-process mode)")
    p.add_argument("--faults", default=None,
                   help="arm a utils/faults.py spec for the measured "
                   "run (after warmup), e.g. "
                   "'serve.engine.execute:raise:0.05' — benchmark "
                   "latency under injected failures (in-process mode)")
    p.add_argument("--json", action="store_true",
                   help="emit one machine-readable serve_bench.v1 JSON "
                   "line at the end")
    p.add_argument("--json-out", dest="json_out",
                   help="also write the serve_bench.v1 report to this "
                   "path (for tools/slo_check.py)")
    args = p.parse_args()

    session = None
    if args.url:
        import urllib.request

        client = HttpClient(args.url)
        health = json.loads(urllib.request.urlopen(
            args.url.rstrip("/") + "/healthz", timeout=10).read())
        nv = health["nv"]
    else:
        os.environ.setdefault("LUX_PLATFORM", "cpu")
        if args.mesh:
            # Virtual devices must exist before the backend initializes:
            # widen XLA_FLAGS now, exactly as the RMAT27 tooling does.
            import math

            from lux_tpu.serve.mesh import parse_mesh_spec
            from lux_tpu.utils.platform import virtual_cpu_flags

            n = math.prod(parse_mesh_spec(args.mesh))
            if n > 1:
                os.environ["XLA_FLAGS"] = virtual_cpu_flags(n)
        import jax

        from lux_tpu.utils import flags

        jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))
        from lux_tpu.graph import generate
        from lux_tpu.serve import ServeConfig, Session

        if args.file:
            graph = args.file
        else:
            graph = generate.rmat(args.scale, 8, seed=1)
        session = Session(graph, ServeConfig(
            max_batch=args.max_batch, window_s=args.window_ms / 1e3,
            max_queue=max(64, 4 * args.workers),
            mesh=args.mesh,
        ))
        client = LocalClient(session)
        nv = session.graph.nv

    if args.swap_at is not None and session is None:
        print("--swap-at requires in-process mode (not --url)",
              file=sys.stderr)
        return 2
    if args.faults and session is None:
        print("--faults requires in-process mode (not --url)",
              file=sys.stderr)
        return 2
    if args.mesh and session is None:
        print("--mesh requires in-process mode (not --url); start the "
              "server under LUX_SERVE_MESH instead", file=sys.stderr)
        return 2
    if args.faults:
        from lux_tpu.utils import faults

        # Armed AFTER warmup so the injected failures land on the
        # serving path the SLO numbers describe, not on builds.
        faults.arm(args.faults)

    w = max(0.0, min(1.0, args.sssp_weight))
    mix = [("sssp", w), ("pagerank", (1 - w) / 2),
           ("components", (1 - w) / 2)]
    tenants = [t.strip() for t in (args.tenants or "").split(",")
               if t.strip()]
    lat: dict = {}
    tlat: dict = {}
    errs: dict = {}
    stop_at = time.monotonic() + args.duration
    threads = [
        threading.Thread(
            target=worker,
            args=(client, mix, nv, stop_at, args.qps, lat, errs, i,
                  tenants[i % len(tenants)] if tenants else None, tlat),
            daemon=True,
        )
        for i in range(args.workers)
    ]
    swap_result: dict = {}
    swap_thread = None
    if args.swap_at is not None:

        def do_swap():
            import numpy as np

            from lux_tpu.graph import EdgeEdits

            time.sleep(args.swap_at)
            g = session.graph
            rng = np.random.default_rng(99)
            n = max(2, g.ne // 100)
            ins = [(int(rng.integers(g.nv)), int(rng.integers(g.nv)))
                   for _ in range(n // 2)]
            dels = [(int(g.col_src[e]), int(g.col_dst[e]))
                    for e in rng.choice(g.ne, size=n - n // 2,
                                        replace=False)]
            errs_before = dict(errs)
            t_s = time.monotonic()
            try:
                summary = session.apply_edits(
                    EdgeEdits.from_lists(insert=ins, delete=dels))
                swap_result.update(
                    version=summary["version"],
                    swap_s=summary["swap_s"],
                    evicted=summary["evicted"],
                    retired=summary["retired"],
                    plans_evicted=summary.get("plans_evicted", 0),
                )
            except Exception as e:
                swap_result.update(error=repr(e),
                                   swap_s=time.monotonic() - t_s)
            swap_result["errors_during_swap"] = sum(
                errs.get(k, 0) - errs_before.get(k, 0)
                for k in set(errs) | set(errs_before)
            )

        swap_thread = threading.Thread(target=do_swap, daemon=True)

    t0 = time.monotonic()
    for t in threads:
        t.start()
    if swap_thread is not None:
        swap_thread.start()
    for t in threads:
        t.join()
    if swap_thread is not None:
        swap_thread.join(120)
    wall = time.monotonic() - t0

    total = sum(len(v) for v in lat.values())
    print(f"\n{args.workers} workers x {wall:.1f}s  "
          f"({'closed loop' if not args.qps else f'{args.qps} qps/worker'})"
          f"  ->  {total} ok ({total / wall:.1f} req/s), errors: "
          f"{errs or 'none'}")
    report = {"schema": "serve_bench.v1",
              "workers": args.workers, "duration_s": wall,
              "requests_ok": total, "rps": total / wall, "errors": errs,
              "apps": {}}
    for app, xs in sorted(lat.items()):
        xs.sort()
        p50 = percentile(xs, 0.50)
        p95 = percentile(xs, 0.95)
        p99 = percentile(xs, 0.99)
        print(f"  {app:<11} n={len(xs):<6} p50={p50 * 1e3:8.2f} ms   "
              f"p95={p95 * 1e3:8.2f} ms   p99={p99 * 1e3:8.2f} ms")
        report["apps"][app] = {"n": len(xs), "p50_s": p50,
                               "p95_s": p95, "p99_s": p99}
    hist = client.batch_histogram()
    if hist:
        parts = [
            f"<={b['le']}: {b['count']}"
            for b in hist["buckets"] if b["count"]
        ]
        mean = hist["sum"] / max(hist["count"], 1)
        print(f"  batches     n={hist['count']} mean_size={mean:.2f}  "
              f"[{', '.join(parts)}]")
        report["batch_size"] = {"count": hist["count"], "mean": mean,
                                "buckets": hist["buckets"]}
    if tenants:
        # Per-tenant latency quantiles from the client side, joined with
        # the server's /costz consumption totals: "tenant X waited this
        # long and spent that much engine time" in one block.
        try:
            costz = client.costz()
        except Exception:
            costz = {}
        report["tenants"] = {}
        for tenant in sorted(tlat):
            xs = sorted(tlat[tenant])
            entry = {"n": len(xs),
                     "p50_s": percentile(xs, 0.50),
                     "p99_s": percentile(xs, 0.99)}
            cost = (costz.get("totals") or {}).get(tenant)
            if cost:
                entry["cost"] = cost
            report["tenants"][tenant] = entry
            cost_str = (
                "engine_s={engine_s:.3f} iters={iterations} "
                "hit/miss={hits}/{misses}".format(**cost) if cost
                else "cost n/a")
            print(f"  tenant {tenant:<11} n={len(xs):<6} "
                  f"p50={entry['p50_s'] * 1e3:8.2f} ms   "
                  f"p99={entry['p99_s'] * 1e3:8.2f} ms   {cost_str}")
    # Server-side counters the SLO gate cares about: shed/reject volume
    # and the sentinel's recompile count (must be 0 post-warmup).
    try:
        stats = client.stats()
    except Exception:
        stats = {}
    batcher = stats.get("batcher", {})
    pool = stats.get("pool", {})
    report["shed"] = int(batcher.get("deadline_expired", 0))
    report["rejected"] = int(batcher.get("rejected", 0))
    report["recompiles"] = int(pool.get("recompiles", 0))
    report["warmup_compiles"] = int(pool.get("warmup_compiles", 0))
    print(f"  server      shed={report['shed']} "
          f"rejected={report['rejected']} "
          f"recompiles={report['recompiles']}")
    mesh = stats.get("mesh")
    if mesh:
        report["mesh"] = {
            "spec": mesh.get("spec"),
            "shape": mesh.get("shape"),
            "num_parts": mesh.get("num_parts"),
            "plans": mesh.get("plans"),
        }
        if session is not None and mesh.get("num_parts", 1) > 1:
            # Per-device collective volume the warm sharded engines move
            # each iteration — the serving half of the PERF.md exchange
            # evidence (the batch half comes from bench_sharded.v1).
            report["mesh"]["exchange_bytes_per_iter"] = (
                session.mesh_exchange_bytes())
        print(f"  mesh        {mesh.get('spec')} "
              f"(parts={mesh.get('num_parts')}), "
              f"plans={mesh.get('plans', {}).get('plans')}")
    if args.faults:
        from lux_tpu.utils import faults

        faults.disarm()
        report["faults"] = {"spec": args.faults,
                            "injected": faults.counts()}
        print(f"  faults      {args.faults} -> "
              f"injected {report['faults']['injected']}")
    if swap_result:
        report["snapshot"] = swap_result
        if "error" in swap_result:
            print(f"  snapshot    SWAP FAILED: {swap_result['error']}")
        else:
            print(f"  snapshot    v{swap_result['version']} swapped in "
                  f"{swap_result['swap_s']:.2f}s mid-run, "
                  f"errors_during_swap="
                  f"{swap_result['errors_during_swap']}")
    if args.json:
        print(json.dumps(report))
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote {args.json_out}")
    if session is not None:
        session.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
