#!/usr/bin/env python3
"""Multi-chip serving smoke test (`make serve-sharded-smoke`).

End-to-end acceptance run for mesh-keyed sharded serving (ISSUE 10), on
a virtual 8-way CPU mesh (XLA host devices — the same trick the RMAT27
tooling uses, so this runs in CI with no TPU):

1. generate a graph, start one session on a 2x4 serving mesh behind the
   HTTP server, and a single-chip reference session in-process;
2. warm the sharded engines, then prove parity: SSSP and components
   bit-identical to the single-chip session AND the host oracle;
   pagerank allclose (float sum order differs across shard boundaries);
3. sustain a concurrent SSSP burst over the warm sharded engines and
   POST /snapshot mid-burst — ZERO failed queries while the swap
   atomically replaces the whole mesh of engines (retired >= the
   engines the burst warmed) and evicts the old partition plan;
4. post-swap answers are bit-identical to the oracle on the merged
   graph, still from sharded engines (pool keys carry the mesh shape);
5. zero recompiles outside expect windows across the entire run — the
   RecompileSentinel proves the warm sharded path never re-traces;
6. /statusz reports the serving mesh (shape, per-mesh pool entries,
   plan-cache stats).

Prints a ``serve_sharded_smoke.v1`` JSON document on the last line.
Scale with LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

MESH = "2x4"
PARTS = 8


def post(base, path, payload, timeout=300):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read()), dict(r.headers)


def main() -> int:
    # The virtual devices must exist before the first jax import touches
    # the backend; serve/mesh.py would do this too, but doing it here
    # keeps the whole process consistent (both sessions share devices).
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(PARTS)
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
    from lux_tpu.models.sssp import reference_sssp
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    scale = flags.get_int("LUX_SMOKE_SCALE")
    g = generate.rmat(scale, 8, seed=3)

    # -- 1: sharded session over HTTP, single-chip reference in-process -
    sharded = Session(g, ServeConfig(max_batch=4, window_s=0.05,
                                     max_queue=256, pagerank_iters=5,
                                     mesh=MESH))
    single = Session(g, ServeConfig(max_batch=4, window_s=0.05,
                                    pagerank_iters=5, mesh="1"))
    server, _ = serve_in_thread(sharded, port=0)
    base = f"http://127.0.0.1:{server.server_address[1]}"
    assert sharded.meshspec.num_parts == PARTS, sharded.meshspec
    print(f"serving rmat scale={scale} (nv={g.nv} ne={g.ne}) on a "
          f"{MESH} virtual mesh ({PARTS} XLA host devices) at {base}")

    # -- 2: warm parity vs single-chip + host oracle --------------------
    roots = [1, 5, 9, 33]
    for r in roots:
        out, _ = post(base, "/query", {"app": "sssp", "start": r,
                                       "full": True})
        got = np.asarray(out["values"], np.uint32)
        np.testing.assert_array_equal(got, reference_sssp(g, r))
        np.testing.assert_array_equal(
            got, np.asarray(single.query("sssp", start=r,
                                         timeout=300)["values"]))
    cc, _ = post(base, "/query", {"app": "components", "full": True})
    np.testing.assert_array_equal(
        np.asarray(cc["values"]),
        np.asarray(single.query("components", timeout=300)["values"]))
    pr, _ = post(base, "/query", {"app": "pagerank", "full": True})
    pr1 = single.query("pagerank", timeout=300)
    assert np.allclose(pr["values"], pr1["values"],
                       rtol=1e-5, atol=1e-8), "pagerank diverged"
    print(f"parity: {len(roots)} sssp roots + components bit-identical "
          "to single-chip and the host oracle; pagerank allclose(1e-5)")

    # -- 3: hot-swap mid-burst over the warm sharded mesh ---------------
    rng = np.random.default_rng(17)
    n_edit = max(2, g.ne // 100)
    ins = [[int(rng.integers(g.nv)), int(rng.integers(g.nv))]
           for _ in range(n_edit // 2)]
    dels = [[int(g.col_src[e]), int(g.col_dst[e])]
            for e in rng.choice(g.ne, size=n_edit - n_edit // 2,
                                replace=False)]
    new_g = DeltaGraph.fresh(g).stack(EdgeEdits.from_lists(
        insert=[tuple(p) for p in ins],
        delete=[tuple(p) for p in dels])).merged()
    burst_roots = [int(r) for r in rng.integers(0, g.nv, size=24)]
    errors = []

    def one(r):
        try:
            out, h = post(base, "/query",
                          {"app": "sssp", "start": r, "full": True})
            return r, int(h["X-Lux-Snapshot"]), out
        except Exception as e:   # any failure fails the smoke
            errors.append((r, repr(e)))
            return None

    with ThreadPoolExecutor(max_workers=9) as tp:
        futs = [tp.submit(one, r) for r in burst_roots[:12]]
        swap_fut = tp.submit(post, base, "/snapshot",
                             {"insert": ins, "delete": dels})
        futs += [tp.submit(one, r) for r in burst_roots[12:]]
        summary, _ = swap_fut.result()
        burst = [f.result() for f in futs]
    assert not errors, f"queries failed during sharded swap: {errors}"
    # Every answer must be bit-identical to the oracle on the version
    # that computed it. The X-Lux-Snapshot header is written at
    # response time, so a query bound to v0 whose response is written
    # just after the flip reports 1 while (correctly) carrying v0's
    # values — tolerated as "straddled". The reverse (a v0 header over
    # v1 data) would mean an admitted query jumped snapshots: a bug.
    n_v0 = straddled = 0
    for r, ver, out in burst:
        got = np.asarray(out["values"], np.uint32)
        if np.array_equal(got, reference_sssp(g, r)):
            n_v0 += 1
            if ver != 0:
                straddled += 1
        else:
            assert ver == 1, (
                f"root {r}: v{ver}-headed answer is not v0's result")
            np.testing.assert_array_equal(got, reference_sssp(new_g, r))
    assert summary["retired"] >= 3, summary   # the whole warmed mesh
    assert summary["plans_evicted"] >= 1, summary
    print(f"hot-swap v0 -> v1 in {summary['swap_s']:.2f}s under load: "
          f"{len(burst)} in-flight queries, 0 failed ({n_v0} answered "
          f"by v0 [{straddled} straddling the flip], "
          f"{len(burst) - n_v0} by v1, each bit-identical to its "
          f"version's oracle); retired {summary['retired']} sharded "
          f"engines + {summary['plans_evicted']} partition plan(s)")

    # -- 4: post-swap parity on the merged graph ------------------------
    for r in roots:
        out, _ = post(base, "/query", {"app": "sssp", "start": r,
                                       "full": True})
        np.testing.assert_array_equal(
            np.asarray(out["values"], np.uint32),
            reference_sssp(new_g, r))
    print(f"post-swap: {len(roots)} roots bit-identical to the host "
          "oracle on the merged graph")

    # -- 4b: burst under the compacted exchange -------------------------
    # Flipping LUX_EXCHANGE mid-process must build NEW engines (pool
    # keys carry the mode) under expect windows, answer bit-identically,
    # and keep the zero-recompile contract.
    os.environ["LUX_EXCHANGE"] = "compact"
    try:
        with ThreadPoolExecutor(max_workers=4) as tp:
            futs = [tp.submit(one, r) for r in burst_roots[:8]]
            compact_burst = [f.result() for f in futs]
        assert not errors, f"queries failed under compact: {errors}"
        for r, _, out in compact_burst:
            np.testing.assert_array_equal(
                np.asarray(out["values"], np.uint32),
                reference_sssp(new_g, r))
    finally:
        del os.environ["LUX_EXCHANGE"]
    print(f"compact burst: {len(compact_burst)} LUX_EXCHANGE=compact "
          "queries on freshly-keyed engines, each bit-identical to the "
          "oracle")

    # -- 5+6: zero recompiles, mesh observability -----------------------
    stats, _ = get(base, "/stats")
    recompiles = stats["pool"]["recompiles"]
    assert recompiles == 0, (
        f"RecompileSentinel saw {recompiles} compile(s) outside expect "
        "windows on the warm sharded path")
    sharded.pool.sentinel.assert_zero_recompiles()
    statusz, _ = get(base, "/statusz")
    mesh = statusz["mesh"]
    assert mesh["shape"] == [2, 4] and mesh["num_parts"] == PARTS, mesh
    assert mesh["pool_entries"].get(MESH, 0) > 0, mesh
    ebytes = sharded.mesh_exchange_bytes()
    assert ebytes and all(v > 0 for v in ebytes.values()), ebytes
    print(f"sentinel: 0 recompiles outside expect windows; /statusz "
          f"mesh={mesh['spec']} pool_entries={mesh['pool_entries']} "
          f"plans={mesh['plans']['plans']}")

    server.shutdown()
    sharded.close()
    single.close()

    doc = {
        "schema": "serve_sharded_smoke.v1",
        "graph": {"scale": scale, "nv": g.nv, "ne": g.ne},
        "mesh": {"spec": MESH, "num_parts": PARTS,
                 "pool_entries": mesh["pool_entries"],
                 "exchange_bytes_per_iter": ebytes},
        "swap": {"version": summary["version"],
                 "swap_s": summary["swap_s"],
                 "retired": summary["retired"],
                 "plans_evicted": summary["plans_evicted"]},
        "in_flight": {"queries": len(burst), "failed": 0,
                      "answered_by_v0": n_v0},
        "compact_burst": {"queries": len(compact_burst), "failed": 0},
        "recompiles": recompiles,
    }
    print("serve-sharded-smoke PASS (mesh-keyed pool, bitwise parity, "
          "swap under load, zero recompiles)")
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
