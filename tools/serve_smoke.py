#!/usr/bin/env python3
"""Serving smoke test (`make serve-smoke`).

End-to-end acceptance run for the serving subsystem (ISSUE 2):

1. generate a tiny graph, write it as .lux, start the HTTP server on an
   ephemeral port (warm engines compiled before traffic);
2. issue one PageRank query plus >= 8 concurrent SSSP root queries
   through the HTTP front end;
3. validate every SSSP response bit-identical to a sequential
   single-source PushExecutor run (and the host BFS oracle), and the
   PageRank response against the numpy oracle;
4. assert >= 1 multi-source batch of size >= 4 actually formed (via the
   `obs` lux_serve_batch_size histogram);
5. assert zero engine builds after warmup (pool miss counter flat across
   the query phase — i.e. zero recompiles).

Observability acceptance (ISSUE 6, `make serve-obs` runs this same
entry point):

6. one request trace-id spans the whole admission->batch->engine->cache
   chain in the Chrome trace (async "b"/"e" events from obs/spans.py);
7. the ``/metrics`` Prometheus scrape parses, includes
   ``lux_xla_compiles_total``, and shows zero serve-phase compiles;
8. ``/statusz`` reports the rolling SLO windows and queue/cache state;
9. an injected deadline miss (deadline_s=0) returns HTTP 504 AND drops
   a valid ``flight.v1`` postmortem in LUX_FLIGHT_DIR that
   tools/flight_summary.py renders.

Scale with LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def post(base, payload, timeout=120):
    req = urllib.request.Request(
        base + "/query", json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read())


def get_text(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return r.read().decode()


def batch_histogram(base):
    for m in get(base, "/metrics.json")["metrics"]:
        if m["name"] == "lux_serve_batch_size":
            return m
    return None


def parse_prometheus(text):
    """Tiny 0.0.4 parser: {(name, frozen-label-string): value}."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, val = line.rsplit(" ", 1)
        name, _, labels = series.partition("{")
        out[(name, labels.rstrip("}"))] = float(val)
    return out


def async_trace_chains(trace_path):
    """trace-id -> set of span names, from the async b/e events."""
    chains = {}
    with open(trace_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if ev.get("ph") in ("b", "e"):
                chains.setdefault(ev["id"], set()).add(ev["name"])
    return chains


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")
    n_sssp = flags.get_int("LUX_SMOKE_QUERIES")

    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu.engine.push import PushExecutor
    from lux_tpu.graph import generate, write_lux
    from lux_tpu.models.pagerank import reference_pagerank
    from lux_tpu.models.sssp import SSSP, reference_sssp
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    from lux_tpu import obs

    g = generate.rmat(scale, 8, seed=1)
    ni = 5
    with tempfile.TemporaryDirectory() as td:
        gpath = os.path.join(td, f"rmat{scale}.lux")
        write_lux(gpath, g)

        # Arm the full observability stack for this run: Chrome trace
        # stream + flight recorder (the spans flag defaults on).
        trace_path = os.path.join(td, "trace.jsonl")
        flight_dir = os.path.join(td, "flight")
        os.makedirs(flight_dir)
        os.environ["LUX_TRACE"] = trace_path
        os.environ["LUX_FLIGHT_DIR"] = flight_dir
        obs.reconfigure()

        # Generous window so even a slow CPU box forms one full batch
        # from the concurrent burst below; real deployments run ~3ms.
        cfg = ServeConfig(
            max_batch=max(4, n_sssp), window_s=0.5, max_queue=256,
            pagerank_iters=ni,
        )
        session = Session(gpath, cfg)
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"

        health = get(base, "/healthz")
        assert health["ok"] and health["nv"] == g.nv, health
        assert health["pool_warm"] and health["engines"] > 0, health
        print(f"server up: nv={health['nv']} ne={health['ne']} "
              f"fingerprint={health['fingerprint']} "
              f"device={health['device']} engines={health['engines']}")

        misses_before = get(base, "/stats")["pool"]["misses"]
        batches_before = (batch_histogram(base) or {"count": 0})["count"]

        # One PageRank + n_sssp concurrent SSSP root queries.
        rng = np.random.default_rng(7)
        roots = [int(r) for r in rng.integers(0, g.nv, size=n_sssp)]
        with ThreadPoolExecutor(max_workers=n_sssp + 1) as tp:
            pr_fut = tp.submit(post, base, {"app": "pagerank", "ni": ni,
                                            "full": True})
            sssp_futs = [
                tp.submit(post, base, {"app": "sssp", "start": r,
                                       "full": True})
                for r in roots
            ]
            pr = pr_fut.result()
            sssp = [f.result() for f in sssp_futs]

        # -- correctness: batched == sequential single-source == oracle --
        for r, out in zip(roots, sssp):
            got = np.asarray(out["values"], dtype=np.uint32)
            ex = PushExecutor(g, SSSP())
            seq_state, _ = ex.run(start=r)
            seq = np.asarray(seq_state.values)
            np.testing.assert_array_equal(got, seq)
            np.testing.assert_array_equal(got, reference_sssp(g, r))
        print(f"sssp: {n_sssp} roots bit-identical to sequential "
              f"single-source runs + oracle")

        pr_got = np.asarray(pr["values"], dtype=np.float32)
        np.testing.assert_allclose(
            pr_got, reference_pagerank(g, ni), rtol=1e-3, atol=1e-7
        )
        print(f"pagerank: {ni}-iteration fixpoint matches oracle")

        # -- batching actually happened --------------------------------
        hist = batch_histogram(base)
        assert hist is not None, "no lux_serve_batch_size histogram"
        new_big = sum(
            b["count"] for b in hist["buckets"]
            if b["le"] == "+Inf" or float(b["le"]) >= 4
        )
        assert hist["count"] > batches_before, "no batches formed"
        assert new_big >= 1, (
            f"no multi-source batch of size >= 4 formed: {hist['buckets']}"
        )
        sizes = [(b["le"], b["count"])
                 for b in hist["buckets"] if b["count"]]
        print(f"batching: {hist['count']} batches, histogram {sizes} "
              f"(>=1 batch of size >=4)")

        # -- zero recompiles after warmup ------------------------------
        stats = get(base, "/stats")
        misses_after = stats["pool"]["misses"]
        assert misses_after == misses_before, (
            f"engines were built during the query phase: "
            f"{misses_before} -> {misses_after}"
        )
        recompiles = stats["pool"].get("recompiles", 0)
        assert recompiles == 0, (
            f"RecompileSentinel saw {recompiles} XLA compile(s) in the "
            "post-warmup query phase"
        )
        print(f"warm pool: {stats['pool']['engines']} engines, "
              f"{stats['pool']['hits']} hits, miss count flat at "
              f"{misses_after}, sentinel recompiles {recompiles}")
        if "latency_s" in stats:
            print(f"latency: p50={stats['latency_s']['p50'] * 1e3:.1f}ms "
                  f"p99={stats['latency_s']['p99'] * 1e3:.1f}ms over "
                  f"{stats['latency_s']['count']} requests")

        # -- one trace-id spans admission->batch->engine->cache --------
        chains = async_trace_chains(trace_path)
        chain_want = {"serve.admit", "serve.queue_wait", "serve.batch",
                      "serve.engine"}
        full = {
            tid: names for tid, names in chains.items()
            if chain_want <= names
            and names & {"serve.cache.put", "serve.cache.get"}
        }
        assert full, (
            f"no single trace-id covers {sorted(chain_want)} + cache; "
            f"chains: { {t: sorted(n) for t, n in chains.items()} }"
        )
        tid, names = next(iter(sorted(full.items())))
        print(f"spans: trace {tid} covers {sorted(names)} "
              f"({len(chains)} traces total)")

        # -- Prometheus scrape -----------------------------------------
        text = get_text(base, "/metrics")
        samples = parse_prometheus(text)
        compile_samples = {
            k: v for k, v in samples.items()
            if k[0] == "lux_xla_compiles_total"
        }
        assert compile_samples, "no lux_xla_compiles_total in /metrics"
        serve_compiles = sum(
            v for k, v in compile_samples.items() if 'phase="serve"' in k[1]
        )
        assert serve_compiles == 0, (
            f"serve-phase XLA compiles in scrape: {compile_samples}"
        )
        assert any(k[0] == "lux_ir_findings_total" for k in samples), text
        assert any(k[0] == "lux_span_seconds_bucket" for k in samples), (
            "span histograms missing from scrape"
        )
        print(f"prometheus: {len(samples)} samples, "
              f"lux_xla_compiles_total serve-phase sum 0")

        # -- /statusz --------------------------------------------------
        sz = get(base, "/statusz")
        windows = sz["windows"]
        assert windows, sz
        some_window = next(iter(windows.values()))
        assert any(a.get("count", 0) > 0 for a in some_window.values()), sz
        assert sz["queue"]["capacity"] > 0
        assert sz["counters"]["recompiles"] == 0, sz
        print(f"statusz: windows {sorted(windows)} "
              f"cache_hit_rate={sz['cache_hit_rate']} "
              f"queue={sz['queue']['depth']}/{sz['queue']['capacity']}")

        # -- injected deadline miss -> 504 + flight.v1 postmortem ------
        fresh = next(r for r in range(g.nv) if r not in set(roots))
        try:
            post(base, {"app": "sssp", "start": fresh, "deadline_s": 0})
            raise AssertionError("deadline_s=0 query did not 504")
        except urllib.error.HTTPError as e:
            assert e.code == 504, f"expected 504, got {e.code}"
        dumps = sorted(
            f for f in os.listdir(flight_dir) if f.endswith(".json")
        )
        assert dumps, "deadline shed produced no flight dump"
        dump_path = os.path.join(flight_dir, dumps[-1])
        doc = json.loads(open(dump_path).read())
        assert doc["schema"] == "flight.v1" and             doc["reason"] == "deadline_shed", doc
        assert doc["traces"] and doc["context"] and doc["flags"], (
            sorted(doc)
        )
        summary = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "flight_summary.py"), dump_path],
            capture_output=True, text=True,
        )
        assert summary.returncode == 0, summary.stderr
        assert "deadline_shed" in summary.stdout
        print(f"flight: 504 -> {os.path.basename(dump_path)} "
              f"({len(doc['traces'])} traces, "
              f"{len(doc['iterations'])} iteration records) — "
              "flight_summary renders OK")

        server.shutdown()
        session.close()
    print("serve-smoke PASS (incl. observability: spans, prometheus, "
          "statusz, flight recorder)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
