#!/usr/bin/env python3
"""SLO regression gate over a serve_bench.v1 report (`make serve-slo`).

Reads the JSON report tools/serve_bench.py wrote with --json-out and
fails (exit 1) when the serving layer regressed:

- any app's p95 or p99 latency exceeds the baseline by more than the
  tolerance (default 25% — CI boxes are noisy; tighten with
  --tolerance for dedicated hardware);
- the RecompileSentinel counted any post-warmup recompile (always a
  hard failure: recompiles are a bug, not noise);
- requests errored, or shed/reject counts grew beyond --max-shed.

Baseline handling follows luxlint's snapshot-or-compare contract: a
missing baseline file is WRITTEN from the current report and the run
passes (first run bootstraps the gate; commit the file to pin it).

    python tools/serve_bench.py --json-out /tmp/bench.json
    python tools/slo_check.py --input /tmp/bench.json \\
        --baseline bench/serve_slo_baseline.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def load(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if doc.get("schema") != "serve_bench.v1":
        raise SystemExit(
            f"slo_check: {path} is not a serve_bench.v1 report "
            f"(schema={doc.get('schema')!r})"
        )
    return doc


def compare(report: dict, base: dict, tolerance: float,
            max_shed: int) -> list:
    """Human-readable regression strings (empty == gate passes)."""
    bad = []
    if report.get("recompiles", 0) > 0:
        bad.append(f"post-warmup recompiles: {report['recompiles']} "
                   "(sentinel must stay at 0)")
    errs = report.get("errors") or {}
    # Shed/reject surface both as client-visible error kinds and server
    # counters; gate on the server's own count.
    shed = report.get("shed", 0) + report.get("rejected", 0)
    if shed > max_shed:
        bad.append(f"shed+rejected = {shed} > --max-shed {max_shed}")
    hard_errs = {k: v for k, v in errs.items()
                 if "Deadline" not in k and "QueueFull" not in k
                 and "HTTPError" not in k}
    if hard_errs:
        bad.append(f"hard client errors: {hard_errs}")
    for app, cur in sorted((report.get("apps") or {}).items()):
        ref = (base.get("apps") or {}).get(app)
        if ref is None:
            continue        # new app: nothing to regress against
        for q in ("p95_s", "p99_s"):
            if q not in cur or q not in ref:
                continue
            limit = ref[q] * (1.0 + tolerance)
            if cur[q] > limit and cur[q] - ref[q] > 1e-4:
                bad.append(
                    f"{app} {q[:-2]}: {cur[q] * 1e3:.2f} ms > baseline "
                    f"{ref[q] * 1e3:.2f} ms * (1 + {tolerance:.2f})"
                )
    return bad


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--input", required=True,
                    help="serve_bench.v1 JSON from serve_bench --json-out")
    ap.add_argument("--baseline", required=True,
                    help="baseline report path (written if missing)")
    ap.add_argument("--tolerance", type=float, default=0.25,
                    help="allowed fractional p95/p99 growth (default 0.25)")
    ap.add_argument("--max-shed", type=int, default=0, dest="max_shed",
                    help="allowed shed+rejected requests (default 0)")
    args = ap.parse_args()

    report = load(args.input)
    if not os.path.exists(args.baseline):
        # Recompiles/errors must be clean even on the bootstrap run —
        # never pin a broken baseline.
        bad = compare(report, {"apps": {}}, args.tolerance, args.max_shed)
        if bad:
            for b in bad:
                print(f"slo_check: FAIL {b}")
            return 1
        parent = os.path.dirname(os.path.abspath(args.baseline))
        os.makedirs(parent, exist_ok=True)
        with open(args.baseline, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"slo_check: baseline written: {args.baseline} "
              f"({len(report.get('apps') or {})} apps) — run again to "
              "compare")
        return 0

    base = load(args.baseline)
    bad = compare(report, base, args.tolerance, args.max_shed)
    for b in bad:
        print(f"slo_check: FAIL {b}")
    if not bad:
        apps = ", ".join(
            f"{a} p95 {v.get('p95_s', 0) * 1e3:.2f}ms"
            for a, v in sorted((report.get("apps") or {}).items())
        )
        print(f"slo_check: OK within {args.tolerance:.0%} of "
              f"{args.baseline} ({apps}; recompiles=0)")
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
