#!/usr/bin/env python3
"""Dynamic-graph smoke test (`make snapshot-smoke`).

End-to-end acceptance run for the snapshot hot-swap subsystem (ISSUE 7):

1. generate a tiny graph, start the HTTP server, warm version 0;
2. seed SSSP + components traffic; every response carries
   ``X-Lux-Snapshot: 0``;
3. build a ~1% edit batch (half inserts, half deletes);
4. POST /snapshot while a concurrent SSSP burst is in flight — ZERO
   failed queries across the swap (the FIFO drain barrier contract);
5. serving flips to version 1 with a new fingerprint; no version-0
   cache keys survive; version-0 engines are retired;
6. post-swap SSSP answers are bit-identical to the host oracle on the
   merged graph;
7. the incrementally refreshed components entry is bit-identical to a
   fresh from-scratch executor on the merged graph, served as a cache
   hit;
8. zero recompiles outside expect windows across the whole run (pool
   sentinel + /stats counters);
9. one trace-id covers serve.snapshot_swap -> snapshot.apply ->
   serve.snapshot_warm (+ the incremental refresh when it ran).

Prints a ``snapshot_smoke.v1`` JSON document on the last line.
Scale with LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def post(base, path, payload, timeout=300):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=30) as r:
        return json.loads(r.read()), dict(r.headers)


def async_trace_chains(trace_path):
    """trace-id -> set of span names, from the async b/e events."""
    chains = {}
    with open(trace_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            ev = json.loads(line)
            if ev.get("ph") in ("b", "e"):
                chains.setdefault(ev["id"], set()).add(ev["name"])
    return chains


def main() -> int:
    from lux_tpu.utils import flags

    scale = flags.get_int("LUX_SMOKE_SCALE")

    os.environ.setdefault("LUX_PLATFORM", "cpu")
    import jax

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    from lux_tpu import obs
    from lux_tpu.engine.push import PushExecutor
    from lux_tpu.graph import DeltaGraph, EdgeEdits, generate
    from lux_tpu.models.components import ConnectedComponents
    from lux_tpu.models.sssp import reference_sssp
    from lux_tpu.serve import ServeConfig, Session
    from lux_tpu.serve.http import serve_in_thread

    g = generate.rmat(scale, 8, seed=3)
    with tempfile.TemporaryDirectory() as td:
        trace_path = os.path.join(td, "trace.jsonl")
        os.environ["LUX_TRACE"] = trace_path
        obs.reconfigure()

        cfg = ServeConfig(max_batch=4, window_s=0.05, max_queue=256,
                          pagerank_iters=3)
        session = Session(g, cfg)
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"

        # -- 1+2: seed traffic on version 0 ----------------------------
        info, hdr = get(base, "/snapshot")
        assert info["version"] == 0 and hdr["X-Lux-Snapshot"] == "0", info
        fp0 = info["fingerprint"]
        seed_roots = [1, 5, 9]
        for r in seed_roots:
            out, hdr = post(base, "/query", {"app": "sssp", "start": r})
            assert hdr["X-Lux-Snapshot"] == "0", hdr
        post(base, "/query", {"app": "components"})
        print(f"v0 serving: nv={info['nv']} ne={info['ne']} "
              f"fp={fp0[:12]} seeded {len(seed_roots)} sssp roots + "
              "components (X-Lux-Snapshot: 0)")

        # -- 3: ~1% edit batch -----------------------------------------
        rng = np.random.default_rng(17)
        n_edit = max(2, g.ne // 100)
        ins = [[int(rng.integers(g.nv)), int(rng.integers(g.nv))]
               for _ in range(n_edit // 2)]
        dels = [[int(g.col_src[e]), int(g.col_dst[e])]
                for e in rng.choice(g.ne, size=n_edit - n_edit // 2,
                                    replace=False)]
        edits = EdgeEdits.from_lists(
            insert=[tuple(p) for p in ins],
            delete=[tuple(p) for p in dels])
        new_g = DeltaGraph.fresh(g).stack(edits).merged()

        # -- 4: swap under concurrent in-flight traffic ----------------
        burst_roots = [int(r) for r in rng.integers(0, g.nv, size=24)]
        errors = []

        def one(r):
            try:
                out, h = post(base, "/query",
                              {"app": "sssp", "start": r, "full": True})
                return r, int(h["X-Lux-Snapshot"]), out
            except Exception as e:   # any failure fails the smoke
                errors.append((r, repr(e)))
                return None

        with ThreadPoolExecutor(max_workers=9) as tp:
            futs = [tp.submit(one, r) for r in burst_roots[:12]]
            swap_fut = tp.submit(post, base, "/snapshot",
                                 {"insert": ins, "delete": dels})
            futs += [tp.submit(one, r) for r in burst_roots[12:]]
            summary, shdr = swap_fut.result()
            burst = [f.result() for f in futs]
        assert not errors, f"queries failed during swap: {errors}"
        assert summary["version"] == 1 and shdr["X-Lux-Snapshot"] == "1", (
            summary)
        # Every answer is correct for the version it reports.
        for r, ver, out in burst:
            want = reference_sssp(g if ver == 0 else new_g, r)
            np.testing.assert_array_equal(
                np.asarray(out["values"], np.uint32), want)
        n_v0 = sum(1 for _, v, _ in burst if v == 0)
        print(f"hot-swap v0 -> v1 in {summary['swap_s']:.2f}s "
              f"(warm {summary['warm_s']:.2f}s): {len(burst)} in-flight "
              f"queries, 0 failed ({n_v0} answered by v0, "
              f"{len(burst) - n_v0} by v1, each correct for its version)")

        # -- 5: serving state flipped cleanly --------------------------
        info, hdr = get(base, "/snapshot")
        assert info["version"] == 1 and hdr["X-Lux-Snapshot"] == "1"
        assert info["fingerprint"] == summary["fingerprint"] != fp0
        assert info["ne"] == new_g.ne, (info["ne"], new_g.ne)
        stale = [k for k in session.cache.keys()
                 if isinstance(k, tuple) and k and k[0] == fp0]
        assert not stale, f"version-0 cache keys survived: {stale}"
        assert summary["retired"] > 0 and summary["evicted"] > 0, summary
        print(f"v1 serving: fp={info['fingerprint'][:12]} "
              f"evicted {summary['evicted']} cache entries, retired "
              f"{summary['retired']} engines, no v0 keys remain")

        # -- 6: post-swap SSSP bitwise vs oracle on merged graph -------
        for r in seed_roots:
            out, _ = post(base, "/query",
                          {"app": "sssp", "start": r, "full": True})
            np.testing.assert_array_equal(
                np.asarray(out["values"], np.uint32),
                reference_sssp(new_g, r))
        print(f"post-swap sssp: {len(seed_roots)} roots bit-identical "
              "to the host oracle on the merged graph")

        # -- 7: incremental refresh correctness + cache hit ------------
        refreshed = summary["refreshed"]
        assert refreshed and refreshed["components"] == 1, refreshed
        # At least the seeded roots refresh; burst queries answered by v0
        # before the flip may have cached more (all refresh together).
        assert refreshed["sssp"] >= len(seed_roots), refreshed
        hits_before = session.cache.stats()["hits"]
        cc = session.query("components", timeout=300)
        assert session.cache.stats()["hits"] == hits_before + 1, (
            "refreshed components entry was not served as a cache hit")
        assert cc.get("incremental") is True, sorted(cc)
        full_state, _ = PushExecutor(new_g, ConnectedComponents()).run()
        np.testing.assert_array_equal(cc["values"],
                                      np.asarray(full_state.values))
        print(f"incremental refresh: components + {refreshed['sssp']} "
              f"sssp roots warm-started "
              f"(touched_frac={refreshed['touched_frac']:.3f}); "
              "components bit-identical to a fresh executor, served "
              "from cache")

        # -- 8: zero recompiles across the whole run -------------------
        stats, _ = get(base, "/stats")
        recompiles = stats["pool"]["recompiles"]
        assert recompiles == 0, (
            f"RecompileSentinel saw {recompiles} compile(s) outside "
            "expect windows across the swap")
        session.pool.sentinel.assert_zero_recompiles()
        print(f"sentinel: 0 recompiles outside expect windows "
              f"({stats['pool']['engines']} live engines, "
              f"{stats['pool']['retired']} retired)")

        # -- 9: one trace-id covers the whole swap ---------------------
        chains = async_trace_chains(trace_path)
        want = {"serve.snapshot_swap", "snapshot.apply",
                "serve.snapshot_warm"}
        full = {t: n for t, n in chains.items() if want <= n}
        assert full, (
            f"no single trace-id covers {sorted(want)}; chains: "
            f"{ {t: sorted(n) for t, n in chains.items()} }")
        tid, names = next(iter(full.items()))
        print(f"spans: trace {tid} covers {sorted(names)}")

        server.shutdown()
        session.close()

        doc = {
            "schema": "snapshot_smoke.v1",
            "graph": {"scale": scale, "nv": g.nv, "ne": g.ne},
            "edits": {"inserts": len(ins), "deletes": len(dels),
                      "frac": round(n_edit / g.ne, 4)},
            "swap": {"old_version": summary["old_version"],
                     "version": summary["version"],
                     "swap_s": summary["swap_s"],
                     "warm_s": summary["warm_s"],
                     "evicted": summary["evicted"],
                     "retired": summary["retired"]},
            "in_flight": {"queries": len(burst), "failed": 0,
                          "answered_by_v0": n_v0},
            "incremental": refreshed,
            "recompiles": recompiles,
            "trace_spans": sorted(names),
        }
    print("snapshot-smoke PASS (hot-swap, drain barrier, incremental "
          "refresh, zero recompiles)")
    print(json.dumps(doc))
    return 0


if __name__ == "__main__":
    sys.exit(main())
