#!/usr/bin/env python3
"""Summarize a LUX_TRACE (Chrome trace_event JSON-lines) or LUX_METRICS
(run-telemetry JSON-lines) file: top-N spans by self time.

Usage:
  python tools/trace_summary.py TRACE.jsonl [-n 10]
  python tools/trace_summary.py TRACE.jsonl --to-chrome out.json
  python tools/trace_summary.py METRICS.jsonl          # run summary mode

Self time = span duration minus the duration of spans nested inside it
on the same (pid, tid) track, so a run-level span does not dwarf the
flushes it contains. ``--to-chrome`` wraps the JSON-lines into the
``{"traceEvents": [...]}`` envelope for drag-and-drop loading in
Perfetto (https://ui.perfetto.dev) or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def read_jsonl(path):
    events = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{ln}: invalid JSON: {e}")
    return events


def is_metrics_dump(events) -> bool:
    return bool(events) and str(
        events[-1].get("schema", "")).startswith("lux.run_telemetry")


def spans_from_events(events):
    """Resolve B/E pairs (and X events) into (name, cat, dur_us, self_us)
    via a per-(pid, tid) stack over time-ordered events."""
    spans = []
    stacks = defaultdict(list)  # (pid, tid) -> [[name, cat, t0, child_us]]
    for ev in sorted(events, key=lambda e: e.get("ts", 0.0)):
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks[key].append([ev.get("name"), ev.get("cat"),
                                ev["ts"], 0.0])
        elif ph == "E":
            stack = stacks[key]
            if not stack:
                print(f"warning: E without B for {ev.get('name')!r}",
                      file=sys.stderr)
                continue
            name, cat, t0, child_us = stack.pop()
            dur = ev["ts"] - t0
            if stack:
                stack[-1][3] += dur
            spans.append((name, cat, dur, max(dur - child_us, 0.0)))
        elif ph == "X":
            dur = ev.get("dur", 0.0)
            spans.append((ev.get("name"), ev.get("cat"), dur, dur))
    for key, stack in stacks.items():
        for name, *_ in stack:
            print(f"warning: unclosed span {name!r} on {key}",
                  file=sys.stderr)
    return spans


def print_top_spans(spans, top_n: int):
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # name -> [count, dur, self]
    for name, _cat, dur, self_us in spans:
        a = agg[name]
        a[0] += 1
        a[1] += dur
        a[2] += self_us
    rows = sorted(agg.items(), key=lambda kv: kv[1][2], reverse=True)
    print(f"{'span':<28} {'count':>6} {'total_ms':>10} {'self_ms':>10} "
          f"{'self/call_ms':>13}")
    for name, (count, dur, self_us) in rows[:top_n]:
        print(f"{name:<28} {count:>6} {dur/1e3:>10.3f} {self_us/1e3:>10.3f} "
              f"{self_us/count/1e3:>13.4f}")


def print_metrics_summary(events, top_n: int):
    run = events[-1]
    print(f"run: engine={run['engine']} program={run.get('program','')} "
          f"nv={run['nv']} ne={run['ne']}")
    print(f"  iters={run['num_iters']} compile={run['compile_s']:.4f}s "
          f"execute={run['execute_s']:.4f}s gteps={run['gteps']:.4f}")
    if run.get("exchange_bytes_per_iter"):
        print(f"  exchange: {run['exchange_bytes_per_iter']} B/iter")
    rows = sorted(run.get("iterations", []),
                  key=lambda r: r["t_iter_s"], reverse=True)
    if rows:
        print(f"  top {min(top_n, len(rows))} iterations by wall time:")
        for r in rows[:top_n]:
            frontier = r.get("frontier")
            print(f"    iter {r['iter']:>5}: {r['t_iter_s']*1e3:.3f} ms"
                  + (f"  frontier={frontier}" if frontier is not None else ""))
    if len(events) > 1:
        print(f"  ({len(events) - 1} earlier run(s) in the file)")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="LUX_TRACE or LUX_METRICS JSON-lines file")
    ap.add_argument("-n", "--top", type=int, default=10,
                    help="rows to show (default 10)")
    ap.add_argument("--to-chrome", metavar="OUT",
                    help="write {'traceEvents': [...]} envelope to OUT for "
                    "Perfetto / chrome://tracing")
    args = ap.parse_args(argv)

    events = read_jsonl(args.path)
    if not events:
        raise SystemExit(f"{args.path}: empty file")

    if is_metrics_dump(events):
        if args.to_chrome:
            raise SystemExit("--to-chrome needs a trace file, not a "
                             "metrics dump")
        print_metrics_summary(events, args.top)
        return 0

    if args.to_chrome:
        with open(args.to_chrome, "w") as f:
            json.dump({"traceEvents": events}, f)
        print(f"wrote {len(events)} events to {args.to_chrome} "
              "(load at https://ui.perfetto.dev)")
        return 0

    print_top_spans(spans_from_events(events), args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
