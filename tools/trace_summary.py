#!/usr/bin/env python3
"""Summarize a LUX_TRACE (Chrome trace_event JSON-lines) or LUX_METRICS
(run-telemetry JSON-lines) file: top-N spans by self time.

Usage:
  python tools/trace_summary.py TRACE.jsonl [-n 10]
  python tools/trace_summary.py TRACE.jsonl --phases   # p50/p95 + phase split
  python tools/trace_summary.py TRACE.jsonl --to-chrome out.json
  python tools/trace_summary.py METRICS.jsonl          # run summary mode
  python tools/trace_summary.py profile_v1.json --phases  # device timeline

``--phases`` also accepts a ``profile.v1`` report (a capture window's
``profile_v1.json``, obs/prof.py): the host-span phase split above is a
wall-clock view; the profile.v1 table is the device-measured one
(interval unions, realized_hidden_frac), rendered via the same
formatter as tools/prof_summary.py.

Self time = span duration minus the duration of spans nested inside it
on the same (pid, tid) track, so a run-level span does not dwarf the
flushes it contains. ``--to-chrome`` wraps the JSON-lines into the
``{"traceEvents": [...]}`` envelope for drag-and-drop loading in
Perfetto (https://ui.perfetto.dev) or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict


def read_jsonl(path):
    events = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{ln}: invalid JSON: {e}")
    return events


def is_metrics_dump(events) -> bool:
    return bool(events) and str(
        events[-1].get("schema", "")).startswith("lux.run_telemetry")


def spans_from_events(events):
    """Resolve B/E pairs (and X events) into (name, cat, dur_us, self_us)
    via a per-(pid, tid) stack over time-ordered events. Async "b"/"e"
    pairs are matched by (name, cat, id) instead — they hop threads, so
    the thread stacks never see them and their self time is the full
    duration."""
    spans = []
    stacks = defaultdict(list)  # (pid, tid) -> [[name, cat, t0, child_us]]
    open_async = {}             # (name, cat, id) -> t0
    for ev in sorted(events, key=lambda e: e.get("ts", 0.0)):
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks[key].append([ev.get("name"), ev.get("cat"),
                                ev["ts"], 0.0])
        elif ph == "E":
            stack = stacks[key]
            if not stack:
                print(f"warning: E without B for {ev.get('name')!r}",
                      file=sys.stderr)
                continue
            name, cat, t0, child_us = stack.pop()
            dur = ev["ts"] - t0
            if stack:
                stack[-1][3] += dur
            spans.append((name, cat, dur, max(dur - child_us, 0.0)))
        elif ph == "X":
            dur = ev.get("dur", 0.0)
            spans.append((ev.get("name"), ev.get("cat"), dur, dur))
        elif ph == "b":
            akey = (ev.get("name"), ev.get("cat"), ev.get("id"))
            open_async.setdefault(akey, ev.get("ts", 0.0))
        elif ph == "e":
            akey = (ev.get("name"), ev.get("cat"), ev.get("id"))
            t0 = open_async.pop(akey, None)
            if t0 is None:
                print(f"warning: async e without b for {ev.get('name')!r} "
                      f"id={ev.get('id')!r}", file=sys.stderr)
                continue
            dur = ev.get("ts", 0.0) - t0
            spans.append((ev.get("name"), ev.get("cat"), dur, dur))
    for key, stack in stacks.items():
        for name, *_ in stack:
            print(f"warning: unclosed span {name!r} on {key}",
                  file=sys.stderr)
    for (name, _cat, id_) in open_async:
        print(f"warning: unclosed async span {name!r} id={id_!r}",
              file=sys.stderr)
    return spans


def counters_from_events(events):
    """Chrome "C" events → name -> list of (ts, {series: value})."""
    series = defaultdict(list)
    for ev in sorted(events, key=lambda e: e.get("ts", 0.0)):
        if ev.get("ph") != "C":
            continue
        vals = ev.get("args") or {}
        series[ev.get("name")].append((ev.get("ts", 0.0), vals))
    return series


def _pct(sorted_vals, q):
    """Linear-interpolated percentile over a pre-sorted list."""
    if not sorted_vals:
        return 0.0
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac


def print_phases(spans, counters):
    """Per-span latency distribution plus the exchange/compute split for
    every engine that reported phase-fenced iterations."""
    by_name = defaultdict(list)
    for name, _cat, dur, _self in spans:
        by_name[name].append(dur)
    print(f"{'span':<28} {'count':>6} {'p50_ms':>9} {'p95_ms':>9} "
          f"{'total_ms':>10}")
    for name in sorted(by_name):
        durs = sorted(by_name[name])
        print(f"{name:<28} {len(durs):>6} {_pct(durs, 0.5)/1e3:>9.3f} "
              f"{_pct(durs, 0.95)/1e3:>9.3f} {sum(durs)/1e3:>10.3f}")
    # Engines with both <engine>.exchange and <engine>.compute spans get
    # a phase-split line: what fraction of fenced time was the collective.
    engines = sorted(
        name[:-len(".exchange")] for name in by_name
        if name.endswith(".exchange")
        and name[:-len(".exchange")] + ".compute" in by_name)
    if engines:
        print()
        print(f"{'engine':<28} {'exchange_ms':>12} {'compute_ms':>11} "
              f"{'exchange_frac':>14}")
        for eng in engines:
            exch = sum(by_name[eng + ".exchange"])
            comp = sum(by_name[eng + ".compute"])
            frac = exch / (exch + comp) if exch + comp > 0 else 0.0
            print(f"{eng:<28} {exch/1e3:>12.3f} {comp/1e3:>11.3f} "
                  f"{frac:>14.3f}")
    # Counter series (e.g. <engine>.phases, <engine>.frontier) summarize
    # as last-sample values — the steady-state view.
    if counters:
        print()
        print(f"{'counter':<28} {'samples':>8}  last")
        for name in sorted(counters):
            pts = counters[name]
            last = ", ".join(f"{k}={v:.4g}" for k, v in pts[-1][1].items())
            print(f"{name:<28} {len(pts):>8}  {last}")


def print_top_spans(spans, top_n: int):
    agg = defaultdict(lambda: [0, 0.0, 0.0])  # name -> [count, dur, self]
    for name, _cat, dur, self_us in spans:
        a = agg[name]
        a[0] += 1
        a[1] += dur
        a[2] += self_us
    rows = sorted(agg.items(), key=lambda kv: kv[1][2], reverse=True)
    print(f"{'span':<28} {'count':>6} {'total_ms':>10} {'self_ms':>10} "
          f"{'self/call_ms':>13}")
    for name, (count, dur, self_us) in rows[:top_n]:
        print(f"{name:<28} {count:>6} {dur/1e3:>10.3f} {self_us/1e3:>10.3f} "
              f"{self_us/count/1e3:>13.4f}")


def print_metrics_summary(events, top_n: int):
    run = events[-1]
    print(f"run: engine={run['engine']} program={run.get('program','')} "
          f"nv={run['nv']} ne={run['ne']}")
    print(f"  iters={run['num_iters']} compile={run['compile_s']:.4f}s "
          f"execute={run['execute_s']:.4f}s gteps={run['gteps']:.4f}")
    if run.get("exchange_bytes_per_iter"):
        print(f"  exchange: {run['exchange_bytes_per_iter']} B/iter")
    rows = sorted(run.get("iterations", []),
                  key=lambda r: r["t_iter_s"], reverse=True)
    if rows:
        print(f"  top {min(top_n, len(rows))} iterations by wall time:")
        for r in rows[:top_n]:
            frontier = r.get("frontier")
            print(f"    iter {r['iter']:>5}: {r['t_iter_s']*1e3:.3f} ms"
                  + (f"  frontier={frontier}" if frontier is not None else ""))
    if len(events) > 1:
        print(f"  ({len(events) - 1} earlier run(s) in the file)")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="LUX_TRACE or LUX_METRICS JSON-lines file")
    ap.add_argument("-n", "--top", type=int, default=10,
                    help="rows to show (default 10)")
    ap.add_argument("--to-chrome", metavar="OUT",
                    help="write {'traceEvents': [...]} envelope to OUT for "
                    "Perfetto / chrome://tracing")
    ap.add_argument("--phases", action="store_true",
                    help="per-span p50/p95 table plus the exchange/compute "
                    "phase split and counter series (engine observatory)")
    args = ap.parse_args(argv)

    # A profile.v1 report is one JSON document, not JSON-lines — detect
    # it first (obs/prof.py capture windows write profile_v1.json).
    try:
        with open(args.path) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, OSError):
        doc = None
    if isinstance(doc, dict) and doc.get("schema") == "profile.v1":
        if not args.phases:
            raise SystemExit("profile.v1 reports need --phases "
                             "(or use tools/prof_summary.py)")
        import os

        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        from lux_tpu.obs import prof

        print(prof.format_report(prof.validate(doc)))
        return 0

    events = read_jsonl(args.path)
    if not events:
        raise SystemExit(f"{args.path}: empty file")

    if is_metrics_dump(events):
        if args.to_chrome or args.phases:
            raise SystemExit("--to-chrome/--phases need a trace file, not "
                             "a metrics dump")
        print_metrics_summary(events, args.top)
        return 0

    if args.phases:
        print_phases(spans_from_events(events), counters_from_events(events))
        return 0

    if args.to_chrome:
        with open(args.to_chrome, "w") as f:
            json.dump({"traceEvents": events}, f)
        print(f"wrote {len(events)} events to {args.to_chrome} "
              "(load at https://ui.perfetto.dev)")
        return 0

    print_top_spans(spans_from_events(events), args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
