#!/usr/bin/env python3
"""Auto-tuner smoke test (`make tune-smoke`).

End-to-end acceptance for the profile-guided auto-tuner (lux_tpu/tune)
on a 2-device virtual CPU mesh, with ``LUX_TUNE_DIR`` and
``LUX_LEDGER_DIR`` armed for the whole run:

1. **known-better selection** — successive halving over the full
   gas_sharded knob space against a seeded synthetic cost model (the
   search's injectable ``measure`` seam) in which the non-default
   compact exchange is known-better: the tuner must select it, and the
   persisted ``tuneconf.v1`` artifact must carry the full score table
   with the tuned-vs-default delta;
2. **real probes** — a second search runs real fixed-iteration probes
   (gas/bfs, tiny budget) so genuine ``tune_probe`` run-ledger records
   from more than one config cohort exist next to the ``tune_select``
   records;
3. **offline verification** — ``luxlint --tune`` over the artifact
   store exits 0 with 0 findings (LUX501-504);
4. **serving warmup applies the winner** — a mesh Session consults the
   TuneCache at warmup and builds bfs engines under the tuned compact
   exchange (engine.exchange_mode proves the overlay took); query
   replies carry ``X-Lux-Tuned`` with the artifact id; apps without an
   artifact are counted fallbacks (``lux_tune_fallback_total``), never
   silent; the sentinel-backed pool counter shows ZERO recompiles after
   warmup — the tuned path adds no per-query compiles;
5. **bitwise parity** — the tuned serving answers for bfs (integral
   depths) are bit-identical to a default-config engine run AND the
   host oracle;
6. **doctor attribution** — ``lux_doctor --tuned`` reads the probe
   ledger back and recognizes the probe cohorts as "tuned config"
   pairs (config diff entirely tuner-managed).

Prints a ``tune_smoke.v1`` JSON document on the last line.
Scale with LUX_SMOKE_SCALE (default 10).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import urllib.request

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PARTS = 2
MESH = "2"


def log(msg):
    print(f"# {msg}", flush=True)


def post(base, payload):
    req = urllib.request.Request(
        base + "/query", json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=300) as r:
        return json.loads(r.read()), dict(r.headers)


def get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def main() -> int:
    # Virtual devices must exist before the first jax backend touch —
    # the same bootstrap serve_sharded_smoke uses.
    os.environ.setdefault("LUX_PLATFORM", "cpu")
    from lux_tpu.utils.platform import virtual_cpu_flags

    os.environ["XLA_FLAGS"] = virtual_cpu_flags(PARTS)
    import jax

    from lux_tpu.utils import flags

    jax.config.update("jax_platforms", flags.get("LUX_PLATFORM"))

    with tempfile.TemporaryDirectory() as td:
        tune_dir = os.path.join(td, "tune")
        ledger_dir = os.path.join(td, "ledger")
        os.environ["LUX_TUNE_DIR"] = tune_dir
        os.environ["LUX_LEDGER_DIR"] = ledger_dir
        # A tiny real-probe budget: the smoke proves the loop closes,
        # not that the search is exhaustive. (The candidate cap is
        # tightened only around the real-probe search in step 2 — the
        # step-1 selection must see the whole knob space.)
        os.environ["LUX_TUNE_PROBE_ITERS"] = "2"
        os.environ["LUX_TUNE_RUNGS"] = "2"

        from lux_tpu.graph import generate
        from lux_tpu.models.bfs import BFS, reference_bfs
        from lux_tpu.obs import ledger, report
        from lux_tpu.tune import load, make_key, tune, tune_cache
        from lux_tpu.utils.checkpoint import fingerprint_hex

        ledger.reset()
        scale = flags.get_int("LUX_SMOKE_SCALE")
        g = generate.rmat(scale, 8, seed=3)
        fp = fingerprint_hex(g)
        device_kind = report.device_profile()["device_kind"]
        tc = tune_cache()
        assert tc.enabled(), "LUX_TUNE_DIR armed above"
        log(f"rmat scale={scale} (nv={g.nv} ne={g.ne}) fp={fp[:12]}.. "
            f"device_kind={device_kind}, tune store {tune_dir}")

        # -- 1. known-better selection over the full knob space ---------
        # Seeded synthetic cost model through the search's injectable
        # measure seam: compact exchange is known-better, full is the
        # default, frontier sits between. The tuner must find compact —
        # deterministically, per LUX_TUNE_SEED (timing a 2-part CPU mesh
        # would make the smoke a coin flip; engine-level phase
        # measurement is exercised by the real probes in step 2).
        assert flags.default("LUX_EXCHANGE") == "full", \
            "smoke assumes full is the default exchange mode"
        base_cost = {"full": 4.0, "compact": 1.0, "frontier": 2.0}

        def measure(cand, iters, rung):
            c = base_cost[cand.get("LUX_EXCHANGE", "full")]
            # Deterministic sub-costs so the score table totally orders.
            c += 0.01 * float(cand.get("LUX_GAS_DENSITY_HI", "0.0625"))
            c += 0.001 * float(cand.get("LUX_GAS_DENSITY_LO", "0.005"))
            return c

        art = tune(g, BFS(), "gas_sharded", program_name="bfs",
                   graph_fingerprint=fp, mesh_shape=MESH,
                   device_kind=device_kind, init_kw={"start": 0},
                   measure=measure)
        assert art["config"]["LUX_EXCHANGE"] == "compact", (
            "tuner must select the known-better non-default exchange",
            art["config"])
        defaults = [r for r in art["score_table"]
                    if r["candidate_index"] == 0]
        assert defaults and defaults[-1]["score"] > art["score"], \
            "score table must carry the tuned-vs-default delta"
        tc.put(art)
        reloaded = load(tune_dir, art["key"])
        assert reloaded is not None and reloaded["id"] == art["id"]
        log(f"selection ok: {art['id']} picked LUX_EXCHANGE=compact over "
            f"default full ({art['score']:.3g} vs "
            f"{defaults[-1]['score']:.3g} s/iter, "
            f"{len(art['score_table'])} probes)")

        # -- 2. real probes feed the run ledger -------------------------
        with flags.overrides({"LUX_TUNE_MAX_CANDIDATES": "3"}):
            art_real = tune(g, BFS(), "gas", program_name="bfs",
                            graph_fingerprint=fp, mesh_shape="1",
                            device_kind=device_kind,
                            init_kw={"start": 0})
        assert art_real["probe_ledger_ids"], \
            "real probes must land runrec.v1 records"
        tc.put(art_real)
        recs = ledger.read_all(ledger_dir, strict=True)
        kinds = sorted({r["kind"] for r in recs})
        probe_hashes = {r["key"]["config_hash"] for r in recs
                        if r["kind"] == "tune_probe"}
        assert "tune_probe" in kinds and "tune_select" in kinds, kinds
        assert len(probe_hashes) >= 2, \
            "probes under different overlays must form distinct cohorts"
        log(f"real probes ok: {art_real['id']} from "
            f"{len(art_real['probe_ledger_ids'])} ledger'd probes, "
            f"{len(probe_hashes)} config cohorts")

        # -- 3. luxlint --tune verifies the store offline ---------------
        lint = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "luxlint.py"),
             "--tune", tune_dir],
            capture_output=True, text=True)
        assert lint.returncode == 0, (lint.returncode, lint.stdout[-800:])
        summary_line = [ln for ln in lint.stdout.splitlines()
                        if ln.startswith("LUXLINT ")][-1]
        lint_doc = json.loads(summary_line[len("LUXLINT "):])
        assert lint_doc["schema"] == "luxlint-tune.v1", lint_doc
        assert lint_doc["findings"] == 0 and lint_doc["files"] == 2, \
            lint_doc
        log(f"luxlint --tune ok: {lint_doc['files']} artifacts, "
            "0 findings")

        # -- 4. serving warmup applies the winner -----------------------
        from lux_tpu.serve import ServeConfig, Session
        from lux_tpu.serve.http import serve_in_thread

        session = Session(g, ServeConfig(max_batch=4, window_s=0.05,
                                         max_queue=128, pagerank_iters=4,
                                         mesh=MESH))
        server, _ = serve_in_thread(session, port=0)
        base = f"http://127.0.0.1:{server.server_address[1]}"
        try:
            prov = session.tuned_for("bfs")
            assert prov and prov["id"] == art["id"], (prov, art["id"])
            engine = session._gas_single("bfs")
            assert engine.exchange_mode == "compact", (
                "warmup must build bfs under the tuned overlay",
                engine.exchange_mode)
            statusz = session.statusz()
            tb = statusz["tune"]
            assert tb["armed"] and "bfs" in tb["artifacts"], tb
            assert tb["artifacts"]["bfs"]["id"] == art["id"], tb
            assert tb["artifacts"]["bfs"]["probes"] == \
                len(art["score_table"]), tb
            assert tb["fallbacks"], \
                "apps without an artifact must show as counted fallbacks"
            fb = sum(
                m["value"] for m in get(base, "/metrics.json")["metrics"]
                if m["name"] == "lux_tune_fallback_total")
            assert fb >= len(tb["fallbacks"]) > 0, (fb, tb["fallbacks"])
            log(f"warmup ok: bfs serves {art['id']} "
                f"(exchange_mode=compact), {len(tb['fallbacks'])} "
                f"counted fallback app(s), fallback_total={int(fb)}")

            # Tuned replies carry provenance; untuned ones must not.
            roots = [1, 5, 9]
            tuned_vals = {}
            for r in roots:
                out, hdr = post(base, {"app": "bfs", "start": r,
                                       "full": True})
                assert hdr.get("X-Lux-Tuned") == art["id"], hdr
                tuned_vals[r] = np.asarray(out["values"], np.int64)
            _pr, hdr = post(base, {"app": "pagerank"})
            assert "X-Lux-Tuned" not in hdr, \
                "fallback apps must not claim tune provenance"
            recompiles = get(base, "/stats")["pool"]["recompiles"]
            assert recompiles == 0, \
                f"tuned path added {recompiles} per-query recompiles"
            log(f"serve ok: {len(roots)} bfs queries with X-Lux-Tuned, "
                "0 recompiles after warmup")

            # -- 5. bitwise parity vs default config + oracle -----------
            from lux_tpu.analysis.ir import build_executor

            default_ex = build_executor("gas_sharded", g, BFS())
            assert default_ex.exchange_mode == "full", \
                default_ex.exchange_mode
            for r in roots:
                st, _ = default_ex.run(start=r)
                np.testing.assert_array_equal(
                    tuned_vals[r],
                    np.asarray(default_ex.gather_values(st), np.int64))
                depth, _parent = reference_bfs(g, r)
                np.testing.assert_array_equal(
                    tuned_vals[r], np.asarray(depth, np.int64))
            log("parity ok: tuned bfs bitwise == default-config engine "
                "== host oracle")
        finally:
            server.shutdown()
            session.close()

        # -- 6. the doctor attributes the tuned cohorts -----------------
        doc_proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "lux_doctor.py"),
             "--tuned", "--json", "--dir", ledger_dir],
            capture_output=True, text=True,
            env=dict(os.environ, JAX_PLATFORMS="cpu"))
        assert doc_proc.returncode in (0, 3), (doc_proc.returncode,
                                               doc_proc.stderr[-800:])
        doctor = json.loads(doc_proc.stdout)
        tuned_pairs = [p for p in doctor["pairs"] if p.get("tuned_config")]
        assert tuned_pairs, (
            "doctor must recognize the probe cohorts as tuned-config "
            "pairs", [p.get("config_diff") for p in doctor["pairs"]])
        log(f"doctor ok: {len(tuned_pairs)}/{len(doctor['pairs'])} "
            "pair(s) attributed to the tuned config")

        os.environ.pop("LUX_TUNE_DIR", None)
        os.environ.pop("LUX_LEDGER_DIR", None)
        tc.clear()
        ledger.reset()

        print(json.dumps({
            "schema": "tune_smoke.v1",
            "ok": True,
            "scale": scale,
            "mesh": MESH,
            "winner": art["config"],
            "winner_id": art["id"],
            "default_score": defaults[-1]["score"],
            "tuned_score": art["score"],
            "real_probe_records": len(art_real["probe_ledger_ids"]),
            "probe_cohorts": len(probe_hashes),
            "lint_findings": lint_doc["findings"],
            "recompiles": recompiles,
            "fallback_total": int(fb),
            "doctor_tuned_pairs": len(tuned_pairs),
        }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
